"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

New engineering for the TPU rebuild (SURVEY §5.7: the reference has no
sequence-parallel support — ``ray.util.collective`` stops at tensor
collectives).  Two strategies over a mesh axis holding sequence shards:

* **Ring attention** (Liu et al.): K/V blocks rotate around the ICI ring via
  ``ppermute`` while each device accumulates blockwise attention with the
  online-softmax (log-sum-exp) recurrence, so peak memory stays
  O(T_local^2-free) and the sequence scales with the ring size.
* **Ulysses**: ``all_to_all`` swaps the sharding between sequence and heads,
  runs dense per-head attention locally, and swaps back — cheaper when
  head_count >= ring size and sequence blocks are small.

Both are pure SPMD functions for use inside ``shard_map``; the ``*_sharded``
wrappers bind them to a mesh.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

NEG_INF = -1e30


def _to_varying(x, axis_name: str):
    """Mark an array as device-varying over the axis (shard_map vma typing;
    no-op on jax versions without pcast)."""
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return x
    try:
        return pcast(x, (axis_name,), to="varying")
    except TypeError:
        return pcast(x, (axis_name,))


def _block_attention_update(q, k, v, m_prev, l_prev, o_prev, mask, sm_scale):
    """One online-softmax block update.

    q: [B, H, Tq, D]; k, v: [B, H, Tk, D]
    m, l: [B, H, Tq]; o: [B, H, Tq, D] (f32 accumulators)
    mask: [Tq, Tk] True = attend.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m_block = s.max(axis=-1)
    m_new = jnp.maximum(m_prev, m_block)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
    alpha = jnp.exp(jnp.where(m_prev == NEG_INF, NEG_INF, m_prev - m_new))
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(mask[None, None, :, :], p, 0.0)
    l_new = l_prev * alpha + p.sum(axis=-1)
    o_new = o_prev * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str, *, causal: bool = True, sm_scale: Optional[float] = None):
    """Blockwise ring attention over sequence shards (call inside shard_map).

    q, k, v: [B, H, T_local, D] — the local sequence shard.
    Returns [B, H, T_local, D] in q.dtype.
    """
    n = lax.axis_size(axis_name)
    my_block = lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    q32 = q.astype(jnp.float32)
    m0 = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    o0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    # inside shard_map the loop carry must be marked device-varying
    m0, l0, o0 = (_to_varying(x, axis_name) for x in (m0, l0, o0))

    q_pos = my_block * Tq + jnp.arange(Tq)

    def body(step, carry):
        k_cur, v_cur, m, l, o = carry
        src_block = (my_block - step) % n  # sequence block k_cur holds now
        if causal:
            k_pos = src_block * Tk + jnp.arange(Tk)
            mask = k_pos[None, :] <= q_pos[:, None]
        else:
            mask = jnp.ones((Tq, Tk), bool)
        m, l, o = _block_attention_update(q32, k_cur, v_cur, m, l, o, mask, scale)
        # rotate K/V to the next rank on the ICI ring
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, o

    _, _, m, l, o = lax.fori_loop(0, n, body, (k, v, m0, l0, o0))
    # fully-masked rows (causal, empty prefix) have l == 0
    l_safe = jnp.where(l == 0, 1.0, l)
    return (o / l_safe[..., None]).astype(q.dtype)


def ring_attention_sharded(
    q, k, v, mesh: Mesh, axis_name: str = "sp", *, causal: bool = True, sm_scale: Optional[float] = None
):
    """Bind ring attention onto a mesh: [B, H, T, D] arrays sharded on T."""
    spec = P(None, None, axis_name, None)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)


# --------------------------------------------------------------------------
# Ulysses-style all-to-all sequence parallelism
# --------------------------------------------------------------------------
def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = True, sm_scale: Optional[float] = None):
    """Head/sequence all-to-all attention (call inside shard_map).

    q, k, v: [B, H, T_local, D] with H divisible by the axis size.  Swaps to
    [B, H_local, T_full, D], runs dense attention, swaps back.
    """
    def swap_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def swap_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    from ray_tpu.ops.attention import mha

    qh, kh, vh = swap_to_heads(q), swap_to_heads(k), swap_to_heads(v)
    out = mha(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return swap_to_seq(out)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, axis_name: str = "sp", *, causal: bool = True, sm_scale=None):
    spec = P(None, None, axis_name, None)
    fn = functools.partial(ulysses_attention, axis_name=axis_name, causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
