"""Exception hierarchy.

Parity with the reference's ``python/ray/exceptions.py``: errors raised inside a
task are captured, stored as the task's result object, and re-raised at
``get()`` time wrapped in :class:`RayTaskError` so the full remote traceback is
visible at the caller.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayTpuError):
    """A task raised an exception; re-raised at the caller on get().

    Mirrors ``python/ray/exceptions.py:RayTaskError`` — carries the remote
    traceback text and the original cause.
    """

    def __init__(self, function_name: str, traceback_str: str, cause: BaseException | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"Task {function_name} failed:\n{traceback_str}")

    @classmethod
    def from_exception(cls, function_name: str, exc: BaseException) -> "RayTaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        return cls(function_name, tb, exc)

    def __reduce__(self):
        # The cause may not be picklable (it crossed a process boundary as
        # text); the traceback string carries the information.
        return (_rebuild_task_error, (self.function_name, self.traceback_str, _maybe_picklable(self.cause)))


def _rebuild_task_error(function_name, traceback_str, cause):
    return RayTaskError(function_name, traceback_str, cause)


def _maybe_picklable(obj):
    import pickle

    if obj is None:
        return None
    try:
        pickle.dumps(obj)
        return obj
    except Exception:
        return None


class RayActorError(RayTpuError):
    """The actor died (creation failure, crash, or intentional kill)."""

    def __init__(self, actor_id=None, message: str = "The actor died unexpectedly."):
        self.actor_id = actor_id
        super().__init__(message)

    def __reduce__(self):
        # default Exception pickling replays __init__ with args=(message,),
        # which would land the message in actor_id; rebuild with both
        return (type(self), (self.actor_id, str(self)))


class ActorDiedError(RayActorError):
    pass


class ActorUnavailableError(RayActorError):
    """Actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object could not be found or reconstructed."""

    def __init__(self, object_id, message: str | None = None):
        self.object_id = object_id
        super().__init__(message or f"Object {object_id} was lost and could not be reconstructed.")

    def __reduce__(self):
        return (type(self), (self.object_id, str(self)))


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    def __init__(self, object_id):
        super().__init__(object_id, f"The owner of object {object_id} has died.")

    def __reduce__(self):
        # narrower __init__ than the base: the message is derived, so only
        # object_id crosses the wire (the base reduce would TypeError)
        return (OwnerDiedError, (self.object_id,))


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get() timed out."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled.")

    def __reduce__(self):
        return (TaskCancelledError, (self.task_id,))


class DeadlineExceededError(RayTpuError):
    """An end-to-end task deadline (``.options(deadline_s=...)``) expired.

    ``stage`` names the lifecycle stage the task was in when the deadline
    fired — ``parked`` (demand queue), ``queued`` (node-local queue),
    ``pulling`` (dependency transfer), or ``executing``.  Deadline failures
    are terminal by design: a task that is already too late must never
    retry (retrying cannot un-miss the deadline), so this error bypasses
    every retry path including ``retry_exceptions``."""

    def __init__(self, task_name: str = "?", stage: str = "?", deadline_s: float | None = None):
        self.task_name = task_name
        self.stage = stage
        self.deadline_s = deadline_s
        budget = f"{deadline_s:.3f}s" if deadline_s is not None else "?"
        super().__init__(
            f"Task {task_name} exceeded its {budget} deadline while {stage}."
        )

    def __reduce__(self):
        return (DeadlineExceededError, (self.task_name, self.stage, self.deadline_s))


class FencedError(RayTpuError):
    """This agent's incarnation was fenced by the head: a newer incarnation
    of its node id registered (or the head declared this node dead while it
    was partitioned).  The agent must self-fence — kill workers, drop its
    store, clear lease pins — and rejoin as a fresh node; none of its
    in-flight commits will be accepted."""

    def __init__(self, node_id=None, incarnation: int | None = None):
        self.node_id = node_id
        self.incarnation = incarnation
        super().__init__(
            f"node incarnation {incarnation} is fenced; re-register as a fresh node"
        )

    def __reduce__(self):
        return (FencedError, (self.node_id, self.incarnation))


class OverloadedError(RayTpuError):
    """Admission control shed this request: a bounded queue at ``layer`` was
    full (or a per-caller cap was hit) and the request was rejected instead
    of growing the queue.  Machine-readable ``retry_after_s`` tells the
    caller when capacity is likely to exist again; the serve proxies map
    this to HTTP 429 with a ``Retry-After`` header (gRPC:
    RESOURCE_EXHAUSTED).  Shedding happens BEFORE any side effect — a shed
    request never executed and is always safe to retry after the hint."""

    def __init__(
        self,
        layer: str = "?",
        reason: str = "queue_full",
        retry_after_s: float = 1.0,
        message: str | None = None,
    ):
        self.layer = layer
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            message
            or f"overloaded at {layer} ({reason}); retry after {retry_after_s:.3g}s"
        )

    def __reduce__(self):
        # str(self) rides along so layer detail (which replica/queue) is
        # not lost crossing process/actor boundaries
        return (
            OverloadedError,
            (self.layer, self.reason, self.retry_after_s, str(self)),
        )


class StoreFullError(RayTpuError):
    """Every tier of the object store — host budget plus the bounded
    disk/spill tier — is full, and the put's backpressure deadline expired
    before deletions freed room.  The put committed NOTHING; the caller can
    free references and retry, or treat it as an overload signal."""

    def __init__(self, waited_s: float = 0.0, needed: int = 0, message: str | None = None):
        self.waited_s = float(waited_s)
        self.needed = int(needed)
        super().__init__(
            message
            or (
                f"object store full (spill tier at capacity); waited "
                f"{waited_s:.2f}s for {needed} bytes of room"
            )
        )

    def __reduce__(self):
        return (StoreFullError, (self.waited_s, self.needed, str(self)))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a task/actor."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's pending-call queue is full (max_pending_calls)."""


class OutOfMemoryError(RayTpuError):
    """Object store / HBM capacity exhausted."""


class CollectiveGroupDeadError(RayTpuError):
    """A rank of an open collective group died: surviving ranks' waits fail
    immediately instead of running out the full rendezvous timeout
    (reference: pending actor calls fail atomically with the death notice,
    ``src/ray/core_worker/transport/direct_actor_task_submitter.h:120``)."""

    def __init__(self, group_name: str, reason: str = ""):
        self.group_name = group_name
        self.reason = reason
        super().__init__(
            f"collective group {group_name!r} lost a participant: {reason or 'rank died'}"
        )

    def __reduce__(self):
        return (CollectiveGroupDeadError, (self.group_name, self.reason))


def raised_copy(exc: BaseException) -> BaseException:
    """A fresh copy of a STORED exception, for raising at a caller.

    Error objects live in the object store (error tombstones, failed-task
    returns) and are served to every getter.  Raising the stored object
    itself attaches each caller's traceback to it — the store entry then
    pins those frames (and every local they reference: ref lists, values)
    for as long as the object lives.  Found by the chaos invariant sweep as
    a refcount "leak" after fault runs; the reference avoids it by
    reconstructing exceptions from their serialized form on every get.
    Falls back to the original object if the copy fails (uncopyable custom
    exception) — correctness over hygiene.
    """
    import copy

    try:
        fresh = copy.copy(exc)
        # copy re-invokes __init__ with args=(message,), which re-formats
        # classes that build their message from a non-message first arg —
        # restore the original args so str(copy) == str(original)
        fresh.args = exc.args
        fresh.__traceback__ = None
        # keep the cause chain visible without sharing OUR traceback back
        # into the stored object
        fresh.__cause__ = exc.__cause__
        return fresh
    except Exception:  # noqa: BLE001
        return exc
