"""Physical operators + StreamingExecutor.

Parity: ``python/ray/data/_internal/execution/`` — physical operators
(``operators/map_operator.py``, ``task_pool_map_operator.py``,
``actor_pool_map_operator.py``, ``limit_operator.py``, ``union``, ``zip``,
all-to-all) driven by a streaming scheduling loop
(``streaming_executor.py:48``; op-selection policy
``streaming_executor_state.py:503 select_operator_to_run``) under
backpressure policies (``backpressure_policy/``) and resource budgets
(``resource_manager.py``).

Execution model: every operator transforms a stream of **RefBundles**
(object refs to blocks + metadata).  Map-like operators launch remote tasks
(or dispatch to an actor pool for class-based UDFs); all-to-all operators
are barriers that run the two-stage exchange in ``shuffle.py``.  The
executor repeatedly picks the runnable operator with the smallest queued
output (pull-based backpressure) so the pipeline streams with bounded
memory instead of materializing every stage.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    BlockMetadata,
    block_from_rows,
    concat_blocks,
    normalize_block,
    split_block,
)
from ray_tpu.data import logical as L


@dataclass
class RefBundle:
    """A group of block refs + their metadata (parity: interfaces.py RefBundle)."""

    refs: List[Any]
    metadata: List[BlockMetadata]

    def num_rows(self) -> int:
        return sum(m.num_rows for m in self.metadata)

    def size_bytes(self) -> int:
        return sum(m.size_bytes for m in self.metadata)


# --------------------------------------------------------------------------
# Map transform chains (what actually runs inside the remote task)
# --------------------------------------------------------------------------
def _apply_stage(stage: L.AbstractMap, blocks: List[Block], udf) -> List[Block]:
    kind = stage.kind
    out: List[Block] = []
    if kind == "map_batches":
        for b in blocks:
            if stage.batch_size is None:
                batches = [b]
            else:
                acc = BlockAccessor(b)
                n = acc.num_rows()
                batches = [acc.slice(i, min(i + stage.batch_size, n)) for i in range(0, n, stage.batch_size)] or []
            for batch in batches:
                fmt = _format_batch(batch, stage.batch_format)
                result = udf(fmt, *stage.fn_args, **stage.fn_kwargs)
                out.append(normalize_block(result))
    elif kind == "map_rows":
        for b in blocks:
            rows = [udf(r, *stage.fn_args, **stage.fn_kwargs) for r in BlockAccessor(b).iter_rows()]
            out.append(block_from_rows(rows))
    elif kind == "filter":
        for b in blocks:
            acc = BlockAccessor(b)
            keep = np.asarray([bool(udf(r)) for r in acc.iter_rows()])
            out.append(acc.take(np.nonzero(keep)[0]) if len(keep) else b)
    elif kind == "flat_map":
        for b in blocks:
            rows = []
            for r in BlockAccessor(b).iter_rows():
                rows.extend(udf(r))
            out.append(block_from_rows(rows))
    else:  # pragma: no cover
        raise ValueError(kind)
    return out


def _format_batch(batch: Block, batch_format: str):
    if batch_format in ("numpy", "default", None):
        return dict(batch)
    if batch_format == "pandas":
        return BlockAccessor(batch).to_pandas()
    if batch_format == "pyarrow":
        return BlockAccessor(batch).to_arrow()
    raise ValueError(f"unknown batch_format {batch_format!r}")


def _run_map_chain(stages: List[L.AbstractMap], udfs: List[Any], block: Block) -> Tuple[Block, BlockMetadata]:
    t0 = time.perf_counter()
    c0 = time.process_time()
    blocks = [block]
    for stage, udf in zip(stages, udfs):
        blocks = _apply_stage(stage, blocks, udf)
    merged = concat_blocks(blocks)
    meta = BlockAccessor(merged).get_metadata(
        exec_time_s=time.perf_counter() - t0, cpu_time_s=time.process_time() - c0
    )
    return merged, meta


# --------------------------------------------------------------------------
# Physical operators
# --------------------------------------------------------------------------
class PhysicalOperator:
    def __init__(self, name: str, input_ops: List["PhysicalOperator"]):
        self.name = name
        self.input_ops = input_ops
        self.inqueues: List[deque] = [deque() for _ in input_ops] or [deque()]
        self.outqueue: deque = deque()
        self.inputs_done: List[bool] = [False for _ in (input_ops or [None])]
        self._completed = False
        self.rows_out = 0
        self.bytes_out = 0
        self.task_time_s = 0.0
        self.cpu_time_s = 0.0
        self.num_tasks = 0
        # per-task/per-block samples for the reference-style stats report
        # (wall/cpu per task, rows/bytes per output block)
        self.wall_samples: List[float] = []
        self.cpu_samples: List[float] = []
        self.row_samples: List[int] = []
        self.byte_samples: List[int] = []
        # DataContext.preserve_order (reference ExecutionOptions.preserve_order):
        # parallel map tasks finish out of order; when set, operators release
        # outputs in DISPATCH order through _emit instead of completion order
        from ray_tpu.data.context import DataContext

        self._preserve_order = DataContext.get_current().preserve_order
        self._seq_counter = 0
        self._next_seq_out = 0
        self._pending_ordered: Dict[int, RefBundle] = {}

    def _next_seq(self) -> int:
        seq = self._seq_counter
        self._seq_counter += 1
        return seq

    def queued_output_count(self) -> int:
        """Finished-but-unconsumed bundles: visible outqueue plus any
        preserve_order hold-back (both are materialized memory)."""
        return len(self.outqueue) + len(self._pending_ordered)

    def queued_output_bytes(self) -> int:
        return sum(b.size_bytes() for b in self.outqueue) + sum(
            b.size_bytes() for b in self._pending_ordered.values()
        )

    def _emit(self, seq: int, bundle: RefBundle) -> None:
        """Release one task's output, reordering to dispatch order when
        preserve_order is set (a missing seq can only be a still-active
        task, so the hold-back always drains)."""
        if not self._preserve_order:
            self.outqueue.append(bundle)
            return
        self._pending_ordered[seq] = bundle
        while self._next_seq_out in self._pending_ordered:
            self.outqueue.append(self._pending_ordered.pop(self._next_seq_out))
            self._next_seq_out += 1

    def record_task_meta(self, meta) -> None:
        """One finished task's BlockMetadata -> stats samples."""
        self.task_time_s += meta.exec_time_s
        self.cpu_time_s += getattr(meta, "cpu_time_s", 0.0)
        self.wall_samples.append(meta.exec_time_s)
        self.cpu_samples.append(getattr(meta, "cpu_time_s", 0.0))

    # -- stream protocol
    def add_input(self, bundle: RefBundle, input_index: int = 0) -> None:
        self.inqueues[input_index].append(bundle)

    def input_done(self, input_index: int = 0) -> None:
        self.inputs_done[input_index] = True

    def all_inputs_done(self) -> bool:
        return all(self.inputs_done)

    def has_next(self) -> bool:
        return bool(self.outqueue)

    def get_next(self) -> RefBundle:
        bundle = self.outqueue.popleft()
        rows, nbytes = bundle.num_rows(), bundle.size_bytes()
        self.rows_out += rows
        self.bytes_out += nbytes
        self.row_samples.append(rows)
        self.byte_samples.append(nbytes)
        return bundle

    # -- scheduling hooks
    def num_active_tasks(self) -> int:
        return 0

    def can_dispatch(self) -> bool:
        return any(self.inqueues)

    def dispatch(self) -> List[Any]:
        """Launch work; returns refs the executor should wait on."""
        return []

    def on_task_done(self, ref: Any) -> None:
        pass

    def completed(self) -> bool:
        return (
            self._completed
            or (self.all_inputs_done() and not any(self.inqueues) and self.num_active_tasks() == 0)
        )

    def shutdown(self) -> None:
        pass


class InputDataBuffer(PhysicalOperator):
    """Source operator holding pre-created bundles (reads or materialized blocks)."""

    def __init__(self, bundles: List[RefBundle]):
        super().__init__("Input", [])
        self.outqueue.extend(bundles)
        self.inputs_done = [True]

    def completed(self) -> bool:
        return not self.outqueue


class TaskPoolMapOperator(PhysicalOperator):
    """Map via stateless remote tasks (parity: task_pool_map_operator.py)."""

    def __init__(self, stages: List[L.AbstractMap], input_op: PhysicalOperator, *, max_concurrency: int = 16):
        name = "->".join(s.name for s in stages)
        super().__init__(name, [input_op])
        self.stages = stages
        self.max_concurrency = max_concurrency
        self._active: Dict[Any, Tuple[Any, int]] = {}  # meta_ref -> (block_ref, seq)
        stages_ser = list(stages)
        udfs = [s.fn for s in stages]
        resources = {"CPU": max(s.num_cpus for s in stages)}
        if any(s.num_tpus for s in stages):
            resources["TPU"] = max(s.num_tpus for s in stages)

        @ray_tpu.remote
        def map_task(block: Block):
            return _run_map_chain(stages_ser, udfs, block)

        self._map_task = map_task.options(num_returns=2, resources=resources)

    def num_active_tasks(self) -> int:
        return len(self._active)

    def can_dispatch(self) -> bool:
        return bool(self.inqueues[0]) and len(self._active) < self.max_concurrency

    def dispatch(self) -> List[Any]:
        bundle = self.inqueues[0].popleft()
        waits = []
        for ref in bundle.refs:
            block_ref, meta_ref = self._map_task.remote(ref)
            self._active[meta_ref] = (block_ref, self._next_seq())
            waits.append(meta_ref)
            self.num_tasks += 1
        return waits

    def on_task_done(self, meta_ref: Any) -> None:
        block_ref, seq = self._active.pop(meta_ref)
        meta = ray_tpu.get(meta_ref)
        self.record_task_meta(meta)
        self._emit(seq, RefBundle([block_ref], [meta]))


class ActorPoolMapOperator(PhysicalOperator):
    """Map via a pool of stateful actors for class-based UDFs
    (parity: actor_pool_map_operator.py; ``compute=ActorPoolStrategy``)."""

    def __init__(self, stages: List[L.AbstractMap], input_op: PhysicalOperator, *, pool_size: int = 2):
        name = "->".join(s.name for s in stages) + f"[actors={pool_size}]"
        super().__init__(name, [input_op])
        self.stages = stages
        stages_ser = list(stages)

        @ray_tpu.remote
        class _MapWorker:
            def __init__(self):
                self._udfs = [
                    s.fn(*s.fn_constructor_args, **s.fn_constructor_kwargs)
                    if isinstance(s.fn, type)
                    else s.fn
                    for s in stages_ser
                ]

            def run(self, block: Block):
                return _run_map_chain(stages_ser, self._udfs, block)

        self._actors = [_MapWorker.remote() for _ in range(pool_size)]
        self._load = {i: 0 for i in range(pool_size)}
        self._active: Dict[Any, Tuple[Any, int, int]] = {}  # (block_ref, actor, seq)
        self.max_tasks_per_actor = 2

    def num_active_tasks(self) -> int:
        return len(self._active)

    def can_dispatch(self) -> bool:
        return bool(self.inqueues[0]) and min(self._load.values()) < self.max_tasks_per_actor

    def dispatch(self) -> List[Any]:
        bundle = self.inqueues[0].popleft()
        waits = []
        for ref in bundle.refs:
            idx = min(self._load, key=self._load.get)
            self._load[idx] += 1
            block_ref, meta_ref = self._actors[idx].run.options(num_returns=2).remote(ref)
            self._active[meta_ref] = (block_ref, idx, self._next_seq())
            waits.append(meta_ref)
            self.num_tasks += 1
        return waits

    def on_task_done(self, meta_ref: Any) -> None:
        block_ref, idx, seq = self._active.pop(meta_ref)
        self._load[idx] -= 1
        meta = ray_tpu.get(meta_ref)
        self.record_task_meta(meta)
        self._emit(seq, RefBundle([block_ref], [meta]))

    def shutdown(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class LimitOperator(PhysicalOperator):
    """Truncates the stream after N rows (parity: limit_operator.py)."""

    def __init__(self, limit: int, input_op: PhysicalOperator):
        super().__init__(f"Limit[{limit}]", [input_op])
        self.limit = limit
        self.taken = 0

    def can_dispatch(self) -> bool:
        return bool(self.inqueues[0])

    def dispatch(self) -> List[Any]:
        bundle = self.inqueues[0].popleft()
        if self.taken >= self.limit:
            return []
        remaining = self.limit - self.taken
        if bundle.num_rows() <= remaining:
            self.taken += bundle.num_rows()
            self.outqueue.append(bundle)
            return []
        # Need to slice: fetch and truncate.
        out_refs, out_meta = [], []
        for ref, meta in zip(bundle.refs, bundle.metadata):
            if remaining <= 0:
                break
            take = min(meta.num_rows, remaining)
            if take == meta.num_rows:
                out_refs.append(ref)
                out_meta.append(meta)
            else:
                block = ray_tpu.get(ref)
                sliced = BlockAccessor(block).slice(0, take)
                out_refs.append(ray_tpu.put(sliced))
                out_meta.append(BlockAccessor(sliced).get_metadata())
            remaining -= take
        self.taken = self.limit
        self.outqueue.append(RefBundle(out_refs, out_meta))
        return []

    def completed(self) -> bool:
        return super().completed() or (self.taken >= self.limit and not self.outqueue)


class UnionOperator(PhysicalOperator):
    def __init__(self, input_ops: List[PhysicalOperator]):
        super().__init__("Union", input_ops)

    def can_dispatch(self) -> bool:
        return any(self.inqueues)

    def dispatch(self) -> List[Any]:
        for q in self.inqueues:
            while q:
                self.outqueue.append(q.popleft())
        return []


class ZipOperator(PhysicalOperator):
    """Barrier: materializes both sides then zips columns
    (parity: zip_operator.py)."""

    def __init__(self, left: PhysicalOperator, right: PhysicalOperator):
        super().__init__("Zip", [left, right])

    def can_dispatch(self) -> bool:
        return self.all_inputs_done() and any(self.inqueues)

    def dispatch(self) -> List[Any]:
        left_refs = [r for b in self.inqueues[0] for r in b.refs]
        right_refs = [r for b in self.inqueues[1] for r in b.refs]
        self.inqueues[0].clear()
        self.inqueues[1].clear()
        left = concat_blocks(ray_tpu.get(left_refs)) if left_refs else {}
        right = concat_blocks(ray_tpu.get(right_refs)) if right_refs else {}
        merged = dict(left)
        for k, v in right.items():
            merged[k + "_1" if k in merged else k] = v
        ref = ray_tpu.put(merged)
        self.outqueue.append(RefBundle([ref], [BlockAccessor(merged).get_metadata()]))
        return []


class AllToAllOperator(PhysicalOperator):
    """Barrier operator running the two-stage exchange (sort/groupby/
    shuffle/repartition) once all input bundles arrive."""

    def __init__(self, logical_op: L.LogicalOp, input_op: PhysicalOperator, *, default_parallelism: int = 8):
        super().__init__(logical_op.name, [input_op])
        self.logical_op = logical_op
        self.default_parallelism = default_parallelism
        self._ran = False

    def can_dispatch(self) -> bool:
        return self.all_inputs_done() and not self._ran

    def num_active_tasks(self) -> int:
        return 0

    def completed(self) -> bool:
        return self._ran and not self.outqueue

    def dispatch(self) -> List[Any]:
        from ray_tpu.data.shuffle import run_exchange

        bundles = [b for q in self.inqueues for b in q]
        self.inqueues[0].clear()
        in_refs = [r for b in bundles for r in b.refs]
        self._ran = True
        if not in_refs:
            return []
        op = self.logical_op
        n_in = len(in_refs)
        if isinstance(op, L.Sort):
            refs, metas = run_exchange(in_refs, kind="sort", n_parts=n_in, key=op.key, descending=op.descending)
        elif isinstance(op, L.Aggregate):
            refs, metas = run_exchange(
                in_refs, kind="groupby", n_parts=min(n_in, self.default_parallelism), key=op.key, aggs=op.aggs
            )
        elif isinstance(op, L.RandomShuffle):
            refs, metas = run_exchange(in_refs, kind="shuffle", n_parts=n_in, seed=op.seed)
        elif isinstance(op, L.Repartition):
            kind = "shuffle" if op.shuffle else "repartition"
            refs, metas = run_exchange(in_refs, kind=kind, n_parts=op.num_blocks, seed=0)
        else:  # pragma: no cover
            raise ValueError(op)
        self.num_tasks += n_in + len(refs)
        for r, m in zip(refs, metas):
            self.outqueue.append(RefBundle([r], [m]))
        return []


class ReadOperator(PhysicalOperator):
    """Executes ReadTasks as remote tasks (parity: plan_read_op.py — reads
    are just map tasks from task descriptors to blocks)."""

    def __init__(self, read_tasks: List[Any], *, max_concurrency: int = 16):
        super().__init__("Read", [])
        self.inputs_done = [True]
        self._pending = deque(read_tasks)
        self._active: Dict[Any, Tuple[Any, int]] = {}  # meta_ref -> (block_ref, seq)
        self.max_concurrency = max_concurrency

        @ray_tpu.remote
        def do_read(task):
            t0 = time.perf_counter()
            blocks = [normalize_block(b) for b in task()]
            merged = concat_blocks(blocks)
            meta = BlockAccessor(merged).get_metadata(
                input_files=task.metadata.input_files, exec_time_s=time.perf_counter() - t0
            )
            return merged, meta

        self._do_read = do_read.options(num_returns=2)

    def num_active_tasks(self) -> int:
        return len(self._active)

    def can_dispatch(self) -> bool:
        return bool(self._pending) and len(self._active) < self.max_concurrency

    def dispatch(self) -> List[Any]:
        task = self._pending.popleft()
        block_ref, meta_ref = self._do_read.remote(task)
        self._active[meta_ref] = (block_ref, self._next_seq())
        self.num_tasks += 1
        return [meta_ref]

    def on_task_done(self, meta_ref: Any) -> None:
        block_ref, seq = self._active.pop(meta_ref)
        meta = ray_tpu.get(meta_ref)
        self.record_task_meta(meta)
        self._emit(seq, RefBundle([block_ref], [meta]))

    def completed(self) -> bool:
        return (
            not self._pending
            and not self._active
            and not self.outqueue
            and not self._pending_ordered
        )


class WriteOperator(PhysicalOperator):
    """Collects blocks and writes via the datasource (driver-side finalize)."""

    def __init__(self, logical_op: L.Write, input_op: PhysicalOperator):
        super().__init__(f"Write{logical_op.datasource.get_name()}", [input_op])
        self.logical_op = logical_op

    def can_dispatch(self) -> bool:
        return self.all_inputs_done() and any(self.inqueues)

    def dispatch(self) -> List[Any]:
        refs = [r for b in self.inqueues[0] for r in b.refs]
        self.inqueues[0].clear()
        blocks = [b for b in ray_tpu.get(refs) if b]
        op = self.logical_op
        op.datasource.write(blocks, op.path, **op.write_kwargs)
        out = block_from_rows([{"num_blocks_written": len(blocks)}])
        self.outqueue.append(RefBundle([ray_tpu.put(out)], [BlockAccessor(out).get_metadata()]))
        return []


# --------------------------------------------------------------------------
# Planner: logical -> physical
# --------------------------------------------------------------------------
def plan(op: L.LogicalOp, ctx) -> PhysicalOperator:
    """Map the optimized logical DAG to physical operators
    (parity: _internal/planner/planner.py)."""
    if isinstance(op, L.Read):
        parallelism = op.parallelism if op.parallelism > 0 else ctx.read_parallelism
        tasks = op.datasource.get_read_tasks(parallelism)
        return ReadOperator(tasks, max_concurrency=ctx.max_tasks_in_flight)
    if isinstance(op, L.InputData):
        bundles = [RefBundle([r], [m]) for r, m in zip(op.refs, op.metadata)]
        return InputDataBuffer(bundles)
    if isinstance(op, (L.FusedMap, L.AbstractMap)):
        upstream = plan(op.inputs[0], ctx)
        stages = op.stages if isinstance(op, L.FusedMap) else [op]
        # compute=ActorPoolStrategy forces the actor pool even for plain
        # function UDFs (parity: ActorPoolStrategy on map_batches); class
        # UDFs always need it (stateful constructors)
        strategy = next(
            (s.compute for s in stages if getattr(s, "compute", None) is not None), None
        )
        if any(isinstance(s.fn, type) for s in stages) or strategy is not None:
            strategy_size = getattr(strategy, "size", None) or getattr(
                strategy, "min_size", None
            )
            conc = op.concurrency
            pool = conc if isinstance(conc, int) else (conc[0] if conc else None)
            return ActorPoolMapOperator(
                stages, upstream, pool_size=pool or strategy_size or 2
            )
        return TaskPoolMapOperator(stages, upstream, max_concurrency=ctx.max_tasks_in_flight)
    if isinstance(op, L.Limit):
        return LimitOperator(op.limit, plan(op.inputs[0], ctx))
    if isinstance(op, L.Union):
        return UnionOperator([plan(i, ctx) for i in op.inputs])
    if isinstance(op, L.Zip):
        return ZipOperator(plan(op.inputs[0], ctx), plan(op.inputs[1], ctx))
    if isinstance(op, (L.Sort, L.Aggregate, L.RandomShuffle, L.Repartition)):
        return AllToAllOperator(op, plan(op.inputs[0], ctx), default_parallelism=ctx.read_parallelism)
    if isinstance(op, L.Write):
        return WriteOperator(op, plan(op.inputs[0], ctx))
    raise ValueError(f"cannot plan {op!r}")


# --------------------------------------------------------------------------
# Streaming executor
# --------------------------------------------------------------------------
class StreamingExecutor:
    """The scheduling loop (parity: streaming_executor.py:48).

    Streams RefBundles through the operator topology; dispatches work on the
    operator with the smallest queued output among runnable ops (the
    reference's ``select_operator_to_run`` memory-pressure heuristic), and
    yields output bundles as soon as the sink produces them.
    """

    def __init__(self, root: PhysicalOperator, ctx):
        self.root = root
        self.ctx = ctx
        self.topology = self._topo_order(root)
        self._waits: Dict[Any, PhysicalOperator] = {}
        self._t_start = time.perf_counter()

    def _topo_order(self, root: PhysicalOperator) -> List[PhysicalOperator]:
        order: List[PhysicalOperator] = []
        seen = set()

        def visit(op):
            if id(op) in seen:
                return
            seen.add(id(op))
            for i in op.input_ops:
                visit(i)
            order.append(op)

        visit(root)
        return order

    def _pump(self) -> None:
        """Move outputs downstream; propagate done-ness."""
        for op in self.topology:
            for consumer in self.topology:
                for idx, producer in enumerate(consumer.input_ops):
                    if producer is op:
                        while op is not self.root and op.has_next():
                            consumer.add_input(op.get_next(), idx)
                        if op.completed():
                            consumer.inputs_done[idx] = True

    def _select_and_dispatch(self) -> bool:
        # ExecutionOptions.resource_limits: cap in-flight task count (cpu)
        # and finished-but-unconsumed bytes (object_store_memory) across the
        # whole topology before considering any further dispatch
        limits = self.ctx.execution_options.resource_limits
        if limits.cpu is not None and sum(
            o.num_active_tasks() for o in self.topology
        ) >= limits.cpu:
            return False
        if limits.object_store_memory is not None and sum(
            o.queued_output_bytes() for o in self.topology
        ) >= limits.object_store_memory:
            return False
        runnable = [op for op in self.topology if op.can_dispatch()]
        if not runnable:
            return False
        # Prefer the op with the least queued output (backpressure), with
        # downstream position as tie-break so data drains toward the sink.
        op = min(runnable, key=lambda o: (o.queued_output_count(), -self.topology.index(o)))
        # Output backpressure: don't let any op run far ahead of its consumer.
        # queued_output_count includes the preserve_order hold-back buffer —
        # blocks parked behind a slow head-of-line task are finished memory
        # and must throttle dispatch exactly like visible outqueue bundles.
        if op.queued_output_count() > self.ctx.max_outqueue_bundles and op is not self.root:
            return False
        for ref in op.dispatch():
            self._waits[ref] = op
        return True

    def run(self) -> Iterator[RefBundle]:
        while True:
            self._pump()
            while self.root.has_next():
                yield self.root.get_next()
            if self.root.completed():
                break
            progressed = self._select_and_dispatch()
            if self._waits:
                ready, _ = ray_tpu.wait(list(self._waits.keys()), num_returns=1, timeout=0.05 if progressed else 1.0)
                for ref in ready:
                    op = self._waits.pop(ref)
                    op.on_task_done(ref)
            elif not progressed:
                self._pump()
                while self.root.has_next():
                    yield self.root.get_next()
                if self.root.completed():
                    break
                time.sleep(0.001)
        for op in self.topology:
            op.shutdown()

    def stats(self) -> "ExecutorStats":
        return ExecutorStats(
            [
                OpStats(
                    op.name, op.num_tasks, op.rows_out, op.bytes_out,
                    op.task_time_s, op.cpu_time_s,
                    list(op.wall_samples), list(op.cpu_samples),
                    list(op.row_samples), list(op.byte_samples),
                )
                for op in self.topology
            ],
            wall_s=time.perf_counter() - self._t_start if self._t_start else 0.0,
        )


# Recent dataset executions (name, wall-clock, per-op stats) for the
# dashboard's Data panel — bounded ring, newest last.
_recent_executions: deque = deque(maxlen=50)
_recent_lock = threading.Lock()


def record_execution(name: str, stats: "ExecutorStats") -> None:
    with _recent_lock:
        _recent_executions.append({"name": name, "ts": time.time(), "stats": stats})


def recent_executions() -> List[dict]:
    with _recent_lock:
        items = list(_recent_executions)
    return [
        {
            "name": it["name"],
            "ts": it["ts"],
            "wall_s": round(it["stats"].wall_s, 4),
            "ops": [
                {
                    "name": op.name,
                    "num_tasks": op.num_tasks,
                    "rows_out": op.rows_out,
                    "bytes_out": op.bytes_out,
                    "task_time_s": round(op.task_time_s, 4),
                    "cpu_time_s": round(op.cpu_time_s, 4),
                }
                for op in it["stats"].ops
            ],
        }
        for it in items
    ]


def _mmmt(samples, fmt) -> str:
    """min/max/mean/total line in the reference's stats format."""
    if not samples:
        return "none"
    return (
        f"{fmt(min(samples))} min, {fmt(max(samples))} max, "
        f"{fmt(sum(samples) / len(samples))} mean, {fmt(sum(samples))} total"
    )


def _t(v: float) -> str:
    return f"{v * 1000:.2f}ms" if v < 1 else f"{v:.2f}s"


def _b(v) -> str:
    v = float(v)
    for unit in ("B", "KB", "MB", "GB"):
        if v < 1024 or unit == "GB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return f"{v:.1f}GB"


@dataclass
class OpStats:
    name: str
    num_tasks: int
    rows_out: int
    bytes_out: int
    task_time_s: float
    cpu_time_s: float = 0.0
    wall_samples: List[float] = field(default_factory=list)
    cpu_samples: List[float] = field(default_factory=list)
    row_samples: List[int] = field(default_factory=list)
    byte_samples: List[int] = field(default_factory=list)


@dataclass
class ExecutorStats:
    ops: List[OpStats]
    wall_s: float = 0.0

    def summary(self) -> str:
        """Per-operator report in the reference's format
        (``python/ray/data/_internal/stats.py`` to_summary — 'Operator N
        <name>: ...' with remote wall/cpu time and output rows/bytes as
        min/max/mean/total lines)."""
        lines = []
        for i, op in enumerate(self.ops):
            blocks = len(op.row_samples)
            lines.append(
                f"Operator {i} {op.name}: {op.num_tasks} tasks executed, "
                f"{blocks} blocks produced"
            )
            if op.wall_samples:
                lines.append(f"* Remote wall time: {_mmmt(op.wall_samples, _t)}")
            if any(op.cpu_samples):
                lines.append(f"* Remote cpu time: {_mmmt(op.cpu_samples, _t)}")
            if op.row_samples:
                lines.append(
                    f"* Output num rows per block: {_mmmt(op.row_samples, lambda v: str(int(v)))}"
                )
            if op.byte_samples:
                lines.append(f"* Output size bytes per block: {_mmmt(op.byte_samples, _b)}")
            lines.append("")
        if self.wall_s:
            lines.append(f"Dataset execution time: {_t(self.wall_s)}")
        return "\n".join(lines).rstrip()


# --------------------------------------------------------------------------
# Training feed: RefBundles -> one deterministic feature matrix
# --------------------------------------------------------------------------
def bundles_to_feature_rows(bundles: Iterator[RefBundle]) -> np.ndarray:
    """Materialize an ORDERED RefBundle stream into one ``[N, F]`` float32
    feature matrix — the global row order elastic training batches index
    into (``train/controller.py global_batch``).

    Columns are flattened in sorted-name order (scalars contribute one
    feature, fixed-width vectors their width), so the matrix — and with it
    every training batch — is a pure function of the dataset contents,
    independent of block boundaries or gang size.  Pass the result of
    ``dataset._execute(preserve_order=True)`` so block order matches the
    logical row order."""
    feature_blocks: List[np.ndarray] = []
    for bundle in bundles:
        for ref in bundle.refs:
            block = normalize_block(ray_tpu.get(ref))
            if not block:
                continue
            cols = []
            for name in sorted(block):
                col = np.asarray(block[name])
                if col.dtype == object:
                    raise TypeError(
                        f"column {name!r} is not numeric; the training feed "
                        "needs numeric features"
                    )
                cols.append(col.reshape(col.shape[0], -1).astype(np.float32))
            feature_blocks.append(np.concatenate(cols, axis=1))
    if not feature_blocks:
        raise ValueError("dataset produced no rows to train on")
    return np.ascontiguousarray(np.concatenate(feature_blocks, axis=0))
