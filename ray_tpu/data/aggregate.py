"""Aggregation functions for groupby/global aggregates.

Parity: ``python/ray/data/aggregate.py`` (AggregateFn with
init/accumulate/merge/finalize; built-ins Count, Sum, Min, Max, Mean, Std,
Unique).  Accumulation is vectorized over numpy columns — per-block partial
aggregates run inside remote map tasks; merge/finalize run in the reduce
stage.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor


class AggregateFn:
    def __init__(
        self,
        init: Callable[[], Any],
        accumulate_block: Callable[[Any, Block], Any],
        merge: Callable[[Any, Any], Any],
        finalize: Callable[[Any], Any],
        name: str,
    ):
        self.init = init
        self.accumulate_block = accumulate_block
        self.merge = merge
        self.finalize = finalize
        self.name = name


class Count(AggregateFn):
    def __init__(self):
        super().__init__(
            init=lambda: 0,
            accumulate_block=lambda a, b: a + BlockAccessor(b).num_rows(),
            merge=lambda a, b: a + b,
            finalize=lambda a: a,
            name="count()",
        )


class _ColumnAgg(AggregateFn):
    def __init__(self, on: str, name: str, init, acc_col, merge, finalize):
        self.on = on
        super().__init__(
            init=init,
            accumulate_block=lambda a, b: merge(a, acc_col(b[on])) if BlockAccessor(b).num_rows() else a,
            merge=merge,
            finalize=finalize,
            name=f"{name}({on})",
        )


class Sum(_ColumnAgg):
    def __init__(self, on: str):
        super().__init__(
            on, "sum",
            init=lambda: 0,
            acc_col=lambda col: col.sum(),
            merge=lambda a, b: a + b,
            finalize=lambda a: _item(a),
        )


class Min(_ColumnAgg):
    def __init__(self, on: str):
        super().__init__(
            on, "min",
            init=lambda: None,
            acc_col=lambda col: col.min(),
            merge=lambda a, b: b if a is None else (a if b is None else min(a, b)),
            finalize=lambda a: _item(a),
        )


class Max(_ColumnAgg):
    def __init__(self, on: str):
        super().__init__(
            on, "max",
            init=lambda: None,
            acc_col=lambda col: col.max(),
            merge=lambda a, b: b if a is None else (a if b is None else max(a, b)),
            finalize=lambda a: _item(a),
        )


class Mean(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: (0.0, 0),
            accumulate_block=lambda a, b: (a[0] + float(b[on].sum()), a[1] + len(b[on])) if len(b.get(on, ())) else a,
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            finalize=lambda a: a[0] / a[1] if a[1] else None,
            name=f"mean({on})",
        )


class Std(AggregateFn):
    """Welford/Chan parallel variance merge (ddof=1, matching the reference)."""

    def __init__(self, on: str, ddof: int = 1):
        self.on = on

        def acc(state, block):
            col = block.get(on)
            if col is None or not len(col):
                return state
            n2, m2_mean, m2 = len(col), float(col.mean()), float(((col - col.mean()) ** 2).sum())
            return _chan_merge(state, (n2, m2_mean, m2))

        super().__init__(
            init=lambda: (0, 0.0, 0.0),
            accumulate_block=acc,
            merge=_chan_merge,
            finalize=lambda s: float(np.sqrt(s[2] / (s[0] - ddof))) if s[0] > ddof else None,
            name=f"std({on})",
        )


class Unique(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        super().__init__(
            init=lambda: set(),
            accumulate_block=lambda a, b: a | set(_tolist(b[on])) if len(b.get(on, ())) else a,
            merge=lambda a, b: a | b,
            finalize=lambda a: sorted(a),
            name=f"unique({on})",
        )


def _chan_merge(a, b):
    n1, mean1, m2_1 = a
    n2, mean2, m2_2 = b
    if n1 == 0:
        return b
    if n2 == 0:
        return a
    n = n1 + n2
    delta = mean2 - mean1
    mean = mean1 + delta * n2 / n
    m2 = m2_1 + m2_2 + delta * delta * n1 * n2 / n
    return (n, mean, m2)


def _item(v):
    return v.item() if isinstance(v, np.generic) else v


def _tolist(col: np.ndarray) -> list:
    return [_item(v) for v in col]
