"""DataIterator: batched consumption, the HBM on-ramp.

Parity: ``python/ray/data/iterator.py:68`` (``iter_batches`` :106,
``iter_torch_batches`` :262).  TPU-first delta: the flagship consumption
path is ``iter_jax_batches`` — host numpy batches staged into HBM via
``jax.device_put`` (optionally sharded over a mesh axis), which is the
Dataset→Train hand-off.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks


class DataIterator:
    def __init__(self, bundle_iter_factory: Callable[[], Iterator], owner=None):
        self._factory = bundle_iter_factory
        self._owner = owner

    # ------------------------------------------------------------- batches
    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        from ray_tpu.data.executor import _format_batch

        def blocks() -> Iterator[Block]:
            for bundle in self._factory():
                for ref in bundle.refs:
                    block = ray_tpu.get(ref)
                    if block and BlockAccessor(block).num_rows():
                        yield block

        source: Iterator[Block] = blocks()
        if local_shuffle_buffer_size:
            source = _shuffle_blocks(source, local_shuffle_buffer_size, local_shuffle_seed)

        carry: Optional[Block] = None
        for block in source:
            if carry:
                block = concat_blocks([carry, block])
                carry = None
            if batch_size is None:
                yield _format_batch(block, batch_format)
                continue
            acc = BlockAccessor(block)
            n = acc.num_rows()
            start = 0
            while n - start >= batch_size:
                yield _format_batch(acc.slice(start, start + batch_size), batch_format)
                start += batch_size
            if start < n:
                carry = acc.slice(start, n)
        if carry and not drop_last and BlockAccessor(carry).num_rows():
            yield _format_batch(carry, batch_format)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for batch in self.iter_batches(batch_size=None, batch_format="numpy"):
            yield from BlockAccessor(batch).iter_rows()

    # --------------------------------------------------------------- jax
    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        dtypes: Optional[Dict[str, Any]] = None,
        device: Optional[Any] = None,
        sharding: Optional[Any] = None,
        drop_last: bool = True,
        local_shuffle_buffer_size: Optional[int] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Yield batches as device-resident ``jax.Array``s.

        With ``sharding`` (a ``jax.sharding.Sharding``), each batch lands
        sharded across the mesh (the data-parallel input pipeline); with
        ``device``, on a single chip; default: JAX's default device.
        """
        import jax

        for batch in self.iter_batches(
            batch_size=batch_size,
            batch_format="numpy",
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
        ):
            out = {}
            for k, v in batch.items():
                if v.dtype == object:
                    out[k] = v  # non-numeric columns stay on host
                    continue
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                if sharding is not None:
                    out[k] = jax.device_put(v, sharding)
                elif device is not None:
                    out[k] = jax.device_put(v, device)
                else:
                    out[k] = jax.device_put(v)
            yield out

    def iter_torch_batches(self, *, batch_size: int = 256, drop_last: bool = False, **kw) -> Iterator[Dict[str, Any]]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", drop_last=drop_last, **kw):
            yield {
                k: torch.from_numpy(np.ascontiguousarray(v)) if v.dtype != object else v
                for k, v in batch.items()
            }

    def iter_tf_batches(self, *, batch_size: int = 256, drop_last: bool = False, **kw) -> Iterator[Dict[str, Any]]:
        """Batches as tf tensors (parity: DataIterator.iter_tf_batches)."""
        import tensorflow as tf

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", drop_last=drop_last, **kw):
            yield {
                k: tf.convert_to_tensor(v) if v.dtype != object else v
                for k, v in batch.items()
            }

    def to_tf(self, feature_columns, label_columns, *, batch_size: int = 256):
        """A tf.data.Dataset over this iterator (parity: Dataset.to_tf):
        yields (features, labels) tuples (single column -> tensor, several
        -> dict of tensors)."""
        import tensorflow as tf

        feats = [feature_columns] if isinstance(feature_columns, str) else list(feature_columns)
        labels = [label_columns] if isinstance(label_columns, str) else list(label_columns)

        def pick(batch, cols):
            if len(cols) == 1:
                return batch[cols[0]]
            return {c: batch[c] for c in cols}

        def fresh():
            for batch in self.iter_tf_batches(batch_size=batch_size):
                yield pick(batch, feats), pick(batch, labels)

        # Probe one batch to build output specs, then hand the SAME
        # iterator (probe batch first) to the first epoch — a single-pass
        # source must not lose its first batch to the spec probe.
        probe_iter = fresh()
        first = next(probe_iter)
        state = {"probe": (probe_iter, first)}

        def gen():
            probe = state.pop("probe", None)
            if probe is not None:
                it, head = probe
                yield head
                yield from it
            else:
                yield from fresh()

        def spec_of(x):
            if isinstance(x, dict):
                return {k: tf.TensorSpec(shape=(None,) + tuple(v.shape[1:]), dtype=v.dtype) for k, v in x.items()}
            return tf.TensorSpec(shape=(None,) + tuple(x.shape[1:]), dtype=x.dtype)

        return tf.data.Dataset.from_generator(
            gen, output_signature=(spec_of(first[0]), spec_of(first[1]))
        )

    def materialize(self):
        if self._owner is not None:
            return self._owner.materialize()
        raise NotImplementedError

    # -------------------------------------------------- metadata + torch
    def schema(self):
        """Schema of the iterated dataset (parity: iterator.py:258) —
        the owner's schema when attached.  Owner-less iterators
        (streaming_split consumers) return None: their source is a
        single-pass queue, and probing a batch to infer dtypes would
        permanently drop those rows from the stream."""
        if self._owner is not None and hasattr(self._owner, "schema"):
            return self._owner.schema()
        return None

    def stats(self) -> str:
        """Execution-timing report (parity: iterator.py:253)."""
        if self._owner is not None and hasattr(self._owner, "stats"):
            return self._owner.stats()
        return ""

    def to_torch(
        self,
        *,
        label_column=None,
        feature_columns=None,
        label_column_dtype=None,
        feature_column_dtypes=None,
        batch_size: int = 1,
        prefetch_batches: int = 1,
        drop_last: bool = False,
        local_shuffle_buffer_size=None,
        local_shuffle_seed=None,
        unsqueeze_label_tensor: bool = True,
        unsqueeze_feature_tensors: bool = True,
    ):
        """Torch IterableDataset of ``(features, label)`` tuples (parity:
        iterator.py:485).  ``feature_columns`` as a list of names packs one
        ``[B, F]`` tensor; a list of name-lists yields a LIST of per-group
        tensors (with ``feature_column_dtypes`` then one dtype per group);
        a dict of name-lists yields a dict of tensors; None packs every
        non-label column (non-numeric columns are dropped with a warning)."""
        import torch

        it = self

        if isinstance(feature_columns, dict) and isinstance(
            feature_column_dtypes, (list, tuple)
        ):
            raise ValueError(
                "to_torch: positional feature_column_dtypes cannot pair with "
                "dict feature_columns (the index would reset per group) — "
                "use a {column: dtype} dict"
            )
        grouped = False
        if isinstance(feature_columns, (list, tuple)) and feature_columns:
            nested = [isinstance(c, (list, tuple)) for c in feature_columns]
            if all(nested):
                grouped = True  # List[List[str]]: one tensor per group
            elif any(nested):
                raise ValueError(
                    "to_torch: feature_columns mixes column names and "
                    "nested lists — use all strings (one [B, F] tensor), "
                    "all lists (a list of per-group tensors), or a dict of "
                    "lists (a dict of tensors)"
                )
        if grouped and isinstance(feature_column_dtypes, (list, tuple)) and len(
            feature_column_dtypes
        ) != len(feature_columns):
            raise ValueError(
                "to_torch: with List[List[str]] feature_columns, "
                "feature_column_dtypes needs one dtype per group "
                f"({len(feature_column_dtypes)} entries for "
                f"{len(feature_columns)} groups)"
            )

        def _features(batch, cols, dtypes):
            ts = []
            for j, c in enumerate(cols):
                t = torch.as_tensor(batch[c])
                if dtypes is not None:
                    if isinstance(dtypes, dict):
                        dt = dtypes.get(c)
                    elif isinstance(dtypes, (list, tuple)):
                        if len(dtypes) != len(cols):
                            raise ValueError(
                                "to_torch: feature_column_dtypes has "
                                f"{len(dtypes)} entries for "
                                f"{len(cols)} feature columns"
                            )
                        dt = dtypes[j]  # positional, parity
                    else:
                        dt = dtypes
                    if dt is not None:
                        t = t.to(dt)
                if t.dim() == 1 and unsqueeze_feature_tensors:
                    t = t.unsqueeze(1)
                ts.append(t)
            if len(ts) == 1:
                return ts[0]
            if any(t.dim() == 1 for t in ts):
                raise ValueError(
                    "to_torch: multiple 1-D feature columns cannot concatenate "
                    "with unsqueeze_feature_tensors=False — keep it True (each "
                    "column becomes [B, 1] before the [B, F] concat)"
                )
            return torch.cat(ts, dim=1)

        class _IterableDS(torch.utils.data.IterableDataset):
            def __iter__(self_ds):
                source = it.iter_batches(
                    batch_size=batch_size,
                    batch_format="numpy",
                    drop_last=drop_last,
                    local_shuffle_buffer_size=local_shuffle_buffer_size,
                    local_shuffle_seed=local_shuffle_seed,
                )
                if prefetch_batches and prefetch_batches > 0:
                    source = _prefetch(source, prefetch_batches)
                warned_dropped = False  # default-selection drop warns once
                for batch in source:
                    label = None
                    if label_column is not None:
                        label = torch.as_tensor(batch[label_column])
                        if label_column_dtype is not None:
                            label = label.to(label_column_dtype)
                        if unsqueeze_label_tensor and label.dim() == 1:
                            label = label.unsqueeze(1)
                    if isinstance(feature_columns, dict):
                        feats = {
                            k: _features(batch, cols, feature_column_dtypes)
                            for k, cols in feature_columns.items()
                        }
                    elif grouped:
                        feats = [
                            _features(
                                batch, list(cols),
                                feature_column_dtypes[gi]
                                if isinstance(feature_column_dtypes, (list, tuple))
                                else feature_column_dtypes,
                            )
                            for gi, cols in enumerate(feature_columns)
                        ]
                    else:
                        import numpy as _np

                        cols = feature_columns
                        if not cols:
                            # default selection skips non-numeric (id/text)
                            # columns — loudly: silently thinner feature
                            # tensors are a training bug nobody can see
                            cols, dropped = [], []
                            for c in batch.keys():
                                if c == label_column:
                                    continue
                                if _np.asarray(batch[c]).dtype.kind in "OUS":
                                    dropped.append(c)
                                else:
                                    cols.append(c)
                            if dropped and not warned_dropped:
                                import warnings

                                warned_dropped = True
                                warnings.warn(
                                    "to_torch: default feature selection "
                                    f"dropped non-numeric column(s) {dropped}; "
                                    "pass feature_columns explicitly to choose "
                                    "(or encode) them",
                                    stacklevel=2,
                                )
                        feats = _features(batch, cols, feature_column_dtypes)
                    yield feats, label

        return _IterableDS()


def _prefetch(source: Iterator[Any], n: int) -> Iterator[Any]:
    """Run the source iterator in a background thread, keeping up to ``n``
    items buffered ahead of the consumer (the ``prefetch_batches`` contract:
    batch formatting/IO overlaps the training step).

    A consumer that stops early (break / next-once / GC) closes this
    generator; the finally block signals the pump, whose timeout-put loop
    notices and exits — no thread or source iterator outlives the consumer.
    """
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=max(1, n))
    END = object()
    stopped = threading.Event()

    def _put(item) -> bool:
        while not stopped.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def pump():
        try:
            for item in source:
                if not _put(item):
                    return
            _put(END)
        except BaseException as exc:  # noqa: BLE001 — re-raised on the consumer
            _put(exc)
        finally:
            close = getattr(source, "close", None)
            if stopped.is_set() and close is not None:
                close()

    threading.Thread(target=pump, daemon=True, name="to-torch-prefetch").start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stopped.set()


def _shuffle_blocks(source: Iterator[Block], buffer_size: int, seed: Optional[int]) -> Iterator[Block]:
    """Local shuffle: accumulate rows into a buffer, emit shuffled slices
    (parity: iterator local_shuffle_buffer_size semantics)."""
    rng = np.random.default_rng(seed)
    buffer: List[Block] = []
    buffered_rows = 0
    for block in source:
        buffer.append(block)
        buffered_rows += BlockAccessor(block).num_rows()
        if buffered_rows >= buffer_size:
            merged = concat_blocks(buffer)
            acc = BlockAccessor(merged)
            perm = rng.permutation(acc.num_rows())
            yield acc.take(perm)
            buffer, buffered_rows = [], 0
    if buffer:
        merged = concat_blocks(buffer)
        acc = BlockAccessor(merged)
        perm = rng.permutation(acc.num_rows())
        yield acc.take(perm)

