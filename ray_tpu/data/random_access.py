"""Random-access serving of a sorted Dataset from a pool of actors.

Parity: ``python/ray/data/random_access_dataset.py`` — sort the dataset by
a key column, spread the sorted blocks across N serving actors, and answer
point lookups (`get_async`) / batched lookups (`multiget`) by binary
search: first over the per-block key ranges to find the block, then inside
the block.  TPU-first note: blocks stay as dict-of-numpy columns, so a
lookup is one `searchsorted` + one row gather — no per-row objects exist
until a row is actually returned.
"""

from __future__ import annotations

import bisect
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor


@ray_tpu.remote(num_cpus=0)
class _BlockServer:
    """Holds a contiguous run of sorted blocks and serves point lookups.

    num_cpus=0 (reference parity: random_access_dataset.py spawns
    zero-CPU serving actors) — lookup serving is lightweight and the pool
    must not starve the cluster's task slots: N workers on an N-CPU
    runtime would otherwise deadlock every later pipeline."""

    def __init__(self, key: str, block_refs: List[Any]):
        # the ACTOR fetches its chunk — blocks never transit the driver
        self._key = key
        self._blocks = ray_tpu.get(list(block_refs))
        # per-block sorted key arrays (the sort already ordered them)
        self._keys = [np.asarray(b[key]) for b in self._blocks]
        self._lookups = 0

    def get(self, block_index: int, key_value) -> Optional[Dict[str, Any]]:
        self._lookups += 1
        keys = self._keys[block_index]
        i = int(np.searchsorted(keys, key_value))
        if i < len(keys) and keys[i] == key_value:
            return BlockAccessor(self._blocks[block_index]).row(i)
        return None

    def multiget(self, block_indices: List[int], key_values: List[Any]) -> List[Optional[dict]]:
        self._lookups += len(key_values)
        out = []
        for bi, kv in zip(block_indices, key_values):
            keys = self._keys[bi]
            i = int(np.searchsorted(keys, kv))
            out.append(
                BlockAccessor(self._blocks[bi]).row(i)
                if i < len(keys) and keys[i] == kv
                else None
            )
        return out

    def stats(self) -> dict:
        return {"blocks": len(self._blocks), "lookups": self._lookups}


class RandomAccessDataset:
    """Created via ``Dataset.to_random_access_dataset(key)``."""

    def __init__(self, ds, key: str, *, num_workers: int = 4):
        sorted_mat = ds.sort(key).materialize()

        # driver fetches only (first_key, num_rows) per block; the raw
        # blocks go to the serving actors BY REFERENCE (a 20 GiB dataset
        # must not transit — let alone peak in — driver memory)
        @ray_tpu.remote
        def block_head(block):
            keys = np.asarray(block.get(key, ()))
            return (keys[0] if len(keys) else None, len(keys))

        heads = ray_tpu.get([block_head.remote(r) for r in sorted_mat._refs])
        refs_and_keys = [
            (ref, first) for ref, (first, n) in zip(sorted_mat._refs, heads) if n > 0
        ]
        if not refs_and_keys:
            raise ValueError("cannot build a random-access view of an empty dataset")
        self._key = key
        # block boundary table: first key of each block (blocks are globally
        # sorted, so block lookup is one bisect over these)
        self._first_keys = [first for _ref, first in refs_and_keys]
        # assign contiguous runs of blocks to workers
        num_workers = max(1, min(num_workers, len(refs_and_keys)))
        per = (len(refs_and_keys) + num_workers - 1) // num_workers
        self._assignments: List[tuple] = []  # global block idx -> (worker idx, local idx)
        self._workers = []
        for w in range(num_workers):
            chunk = refs_and_keys[w * per : (w + 1) * per]
            if not chunk:
                break
            self._workers.append(_BlockServer.remote(key, [r for r, _k in chunk]))
            for local, _ in enumerate(chunk):
                self._assignments.append((len(self._workers) - 1, local))

    def _locate(self, key_value) -> tuple:
        # rightmost block whose first key <= key_value
        i = bisect.bisect_right(self._first_keys, key_value) - 1
        return self._assignments[max(0, i)]

    def get_async(self, key_value):
        """ObjectRef of the matching row dict (None when absent)."""
        w, local = self._locate(key_value)
        return self._workers[w].get.remote(local, key_value)

    def multiget(self, key_values: List[Any]) -> List[Optional[dict]]:
        """Batched lookup: one RPC per worker, results in input order."""
        per_worker: Dict[int, List[tuple]] = {}
        for pos, kv in enumerate(key_values):
            w, local = self._locate(kv)
            per_worker.setdefault(w, []).append((pos, local, kv))
        results: List[Optional[dict]] = [None] * len(key_values)
        futs = []
        for w, items in per_worker.items():
            futs.append(
                (items, self._workers[w].multiget.remote(
                    [local for _pos, local, _kv in items],
                    [kv for _pos, _local, kv in items],
                ))
            )
        for items, fut in futs:
            for (pos, _local, _kv), row in zip(items, ray_tpu.get(fut)):
                results[pos] = row
        return results

    def stats(self) -> str:
        parts = ray_tpu.get([w.stats.remote() for w in self._workers])
        lines = [f"RandomAccessDataset(key={self._key!r}, workers={len(self._workers)})"]
        for i, s in enumerate(parts):
            lines.append(f"  worker {i}: {s['blocks']} blocks, {s['lookups']} lookups")
        return "\n".join(lines)
