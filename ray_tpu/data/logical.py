"""Logical plan: declarative operator DAG built by Dataset transformations.

Parity: ``python/ray/data/_internal/logical/`` — Datasets accumulate logical
operators lazily; a rule-based optimizer (``optimizers.py``) rewrites the
plan (map fusion, limit pushdown) before planning physical execution.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union


class LogicalOp:
    """A node in the logical DAG.  ``inputs`` are upstream LogicalOps."""

    name = "Op"

    def __init__(self, inputs: List["LogicalOp"]):
        self.inputs = inputs

    def __repr__(self) -> str:
        return f"{self.name}"


class Read(LogicalOp):
    name = "Read"

    def __init__(self, datasource, parallelism: int = -1):
        super().__init__([])
        self.datasource = datasource
        self.parallelism = parallelism

    def __repr__(self) -> str:
        return f"Read{self.datasource.get_name()}"


class InputData(LogicalOp):
    """Already-materialized block refs injected into a plan."""

    name = "InputData"

    def __init__(self, refs: List[Any], metadata: List[Any]):
        super().__init__([])
        self.refs = refs
        self.metadata = metadata


class AbstractMap(LogicalOp):
    """Any row/batch-wise transform — fusable with its upstream map.

    ``kind`` is one of: map_rows, map_batches, filter, flat_map.
    """

    def __init__(
        self,
        input_op: LogicalOp,
        kind: str,
        fn: Callable,
        *,
        fn_args: tuple = (),
        fn_kwargs: Optional[dict] = None,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[Any] = None,
        num_cpus: float = 1,
        num_tpus: float = 0,
        concurrency: Optional[Union[int, Tuple[int, int]]] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
    ):
        super().__init__([input_op])
        self.kind = kind
        self.fn = fn
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs or {}
        self.batch_size = batch_size
        self.batch_format = batch_format
        self.compute = compute
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus
        self.concurrency = concurrency
        self.fn_constructor_args = fn_constructor_args
        self.fn_constructor_kwargs = fn_constructor_kwargs or {}

    @property
    def name(self) -> str:  # type: ignore[override]
        base = {"map_rows": "Map", "map_batches": "MapBatches", "filter": "Filter", "flat_map": "FlatMap"}[self.kind]
        fn_name = getattr(self.fn, "__name__", type(self.fn).__name__)
        return f"{base}({fn_name})"

    def uses_actors(self) -> bool:
        return self.concurrency is not None and not callable(self.fn) is False and isinstance(self.fn, type)


class FusedMap(AbstractMap):
    """Result of fusing a chain of maps (optimizer output)."""

    def __init__(self, stages: List[AbstractMap]):
        first = stages[0]
        LogicalOp.__init__(self, first.inputs)
        self.stages = stages
        self.kind = "fused"
        self.batch_size = next((s.batch_size for s in stages if s.batch_size), None)
        self.num_cpus = max(s.num_cpus for s in stages)
        self.num_tpus = max(s.num_tpus for s in stages)
        self.concurrency = next((s.concurrency for s in stages if s.concurrency is not None), None)
        self.fn = None

    @property
    def name(self) -> str:  # type: ignore[override]
        return "->".join(s.name for s in self.stages)


class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, input_op: LogicalOp, limit: int):
        super().__init__([input_op])
        self.limit = limit


class Repartition(LogicalOp):
    name = "Repartition"

    def __init__(self, input_op: LogicalOp, num_blocks: int, shuffle: bool = False):
        super().__init__([input_op])
        self.num_blocks = num_blocks
        self.shuffle = shuffle


class RandomShuffle(LogicalOp):
    name = "RandomShuffle"

    def __init__(self, input_op: LogicalOp, seed: Optional[int] = None):
        super().__init__([input_op])
        self.seed = seed


class Sort(LogicalOp):
    name = "Sort"

    def __init__(self, input_op: LogicalOp, key: Union[str, List[str]], descending: bool = False):
        super().__init__([input_op])
        self.key = key
        self.descending = descending


class Aggregate(LogicalOp):
    name = "Aggregate"

    def __init__(self, input_op: LogicalOp, key: Optional[str], aggs: List[Any]):
        super().__init__([input_op])
        self.key = key
        self.aggs = aggs


class Union(LogicalOp):
    name = "Union"

    def __init__(self, inputs: List[LogicalOp]):
        super().__init__(inputs)


class Zip(LogicalOp):
    name = "Zip"

    def __init__(self, left: LogicalOp, right: LogicalOp):
        super().__init__([left, right])


class Write(LogicalOp):
    name = "Write"

    def __init__(self, input_op: LogicalOp, datasource, path: str, write_kwargs: Optional[dict] = None):
        super().__init__([input_op])
        self.datasource = datasource
        self.path = path
        self.write_kwargs = write_kwargs or {}


# --------------------------------------------------------------------------
# Optimizer (parity: _internal/logical/optimizers.py rule passes)
# --------------------------------------------------------------------------
def optimize(op: LogicalOp) -> LogicalOp:
    op = _rewrite(op, _fuse_maps)
    op = _rewrite(op, _push_limit_into_read)
    return op


def _rewrite(op: LogicalOp, rule: Callable[[LogicalOp], LogicalOp]) -> LogicalOp:
    op.inputs = [_rewrite(i, rule) for i in op.inputs]
    return rule(op)


def _fuse_maps(op: LogicalOp) -> LogicalOp:
    """Fuse chains of compatible maps into one stage (MapFusionRule parity).

    Two maps fuse when neither uses a class-based (actor) transform with
    different concurrency and their resource requests are compatible.
    """
    if not isinstance(op, AbstractMap) or isinstance(op, FusedMap):
        return op
    child = op.inputs[0]
    if isinstance(child, FusedMap) and _fusable(child, op):
        child.stages.append(op)
        child.batch_size = child.batch_size or op.batch_size
        child.num_cpus = max(child.num_cpus, op.num_cpus)
        child.num_tpus = max(child.num_tpus, op.num_tpus)
        return child
    if isinstance(child, AbstractMap) and not isinstance(child, FusedMap) and _fusable(child, op):
        fused = FusedMap([child, op])
        return fused
    return op


def _fusable(a: AbstractMap, b: AbstractMap) -> bool:
    a_conc = getattr(a, "concurrency", None)
    return (a_conc is None) == (b.concurrency is None) and a_conc == b.concurrency


def _push_limit_into_read(op: LogicalOp) -> LogicalOp:
    if isinstance(op, Limit) and isinstance(op.inputs[0], Read):
        read = op.inputs[0]
        read.parallelism = min(read.parallelism, op.limit) if read.parallelism > 0 else read.parallelism
    return op


def plan_to_string(op: LogicalOp, indent: int = 0) -> str:
    lines = [" " * indent + repr(op)]
    for i in op.inputs:
        lines.append(plan_to_string(i, indent + 2))
    return "\n".join(lines)
