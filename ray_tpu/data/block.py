"""Blocks: the unit of data a Dataset is partitioned into.

Parity with the reference's block model (``python/ray/data/block.py:57`` —
``Block = Union[pyarrow.Table, pandas.DataFrame]`` with a ``BlockAccessor``
:221 abstracting over formats).

TPU-first delta: the canonical in-memory format is **columnar numpy** —
``{column: np.ndarray}`` — because the consumption path is
``iter_batches -> jax.device_put`` and numpy columns are the zero-copy host
staging format for HBM transfers.  Arrow/pandas interop is provided behind
optional imports rather than being the core representation.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple, Union

import numpy as np

# A Block is a columnar batch: column name -> numpy array (first dim = rows).
Block = Dict[str, np.ndarray]

# Default column name used when the user supplies bare values (parity:
# ray.data's TENSOR_COLUMN_NAME / "item" convention for simple datasets).
ITEM_COLUMN = "item"


@dataclass
class BlockMetadata:
    """Summary stats the planner/executor track per block without fetching it.

    Parity: ``python/ray/data/block.py`` BlockMetadata (num_rows, size_bytes,
    schema, input_files, exec_stats).
    """

    num_rows: int
    size_bytes: int
    schema: Optional[Dict[str, Any]] = None
    input_files: List[str] = field(default_factory=list)
    exec_time_s: float = 0.0
    cpu_time_s: float = 0.0


def _as_array(values: Any) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    arr = np.asarray(values)
    if arr.dtype == object:
        # Ragged / heterogeneous python objects: keep as object array.
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = v
        return out
    return arr


def block_from_rows(rows: List[Dict[str, Any]]) -> Block:
    """Build a columnar block from a list of row dicts."""
    if not rows:
        return {}
    cols: Dict[str, List[Any]] = {k: [] for k in rows[0]}
    for row in rows:
        if row.keys() != cols.keys():
            for k in row:
                if k not in cols:
                    cols[k] = [None] * (len(next(iter(cols.values()))) - 0)
        for k in cols:
            cols[k].append(row.get(k))
    return {k: _as_array(v) for k, v in cols.items()}


def block_from_items(items: List[Any]) -> Block:
    """Build a block from bare python values (wrapped in the item column)."""
    if items and isinstance(items[0], dict):
        return block_from_rows(items)
    return {ITEM_COLUMN: _as_array(items)}


class BlockAccessor:
    """Accessor over a columnar block (parity: ``block.py:221``)."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(normalize_block(block))

    def to_block(self) -> Block:
        return self._block

    # ------------------------------------------------------------- shape
    def num_rows(self) -> int:
        if not self._block:
            return 0
        return len(next(iter(self._block.values())))

    def size_bytes(self) -> int:
        total = 0
        for arr in self._block.values():
            if arr.dtype == object:
                total += sum(_sizeof(v) for v in arr)
            else:
                total += arr.nbytes
        return total

    def schema(self) -> Optional[Dict[str, Any]]:
        if not self._block:
            return None
        return {k: (v.dtype, v.shape[1:]) for k, v in self._block.items()}

    def get_metadata(
        self,
        input_files: Optional[List[str]] = None,
        exec_time_s: float = 0.0,
        cpu_time_s: float = 0.0,
    ) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=input_files or [],
            exec_time_s=exec_time_s,
            cpu_time_s=cpu_time_s,
        )

    # ------------------------------------------------------------- slicing
    def slice(self, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in self._block.items()}

    def take(self, indices: np.ndarray) -> Block:
        return {k: v[indices] for k, v in self._block.items()}

    # ------------------------------------------------------------- rows
    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        n = self.num_rows()
        keys = list(self._block.keys())
        for i in range(n):
            yield {k: _unbox(self._block[k][i]) for k in keys}

    def row(self, i: int) -> Dict[str, Any]:
        return {k: _unbox(v[i]) for k, v in self._block.items()}

    # ------------------------------------------------------------- sorting
    def sort_indices(self, key: Union[str, List[str]], descending: bool = False) -> np.ndarray:
        keys = [key] if isinstance(key, str) else list(key)
        # np.lexsort sorts by the LAST key first; reverse for precedence.
        arrays = [self._block[k] for k in reversed(keys)]
        idx = np.lexsort([_sortable(a) for a in arrays])
        if descending:
            idx = idx[::-1]
        return idx

    def sort(self, key: Union[str, List[str]], descending: bool = False) -> Block:
        return self.take(self.sort_indices(key, descending))

    # ------------------------------------------------------------- interop
    def to_pandas(self):
        import pandas as pd  # baked in via torch/transformers deps

        return pd.DataFrame({k: list(v) if v.dtype == object else v for k, v in self._block.items()})

    def to_numpy(self, column: Optional[str] = None):
        if column is not None:
            return self._block[column]
        if len(self._block) == 1:
            return next(iter(self._block.values()))
        return dict(self._block)

    def to_arrow(self):
        import pyarrow as pa

        return pa.table({k: list(v) for k, v in self._block.items()})


def normalize_block(block: Any) -> Block:
    """Coerce user-returned batch data into the canonical columnar form."""
    if isinstance(block, dict):
        return {k: _as_array(v) for k, v in block.items()}
    if isinstance(block, np.ndarray):
        return {ITEM_COLUMN: block}
    if isinstance(block, list):
        return block_from_items(block)
    try:  # pandas DataFrame
        import pandas as pd

        if isinstance(block, pd.DataFrame):
            return {k: _as_array(block[k].to_numpy()) for k in block.columns}
    except ImportError:  # pragma: no cover
        pass
    try:  # pyarrow Table
        import pyarrow as pa

        if isinstance(block, pa.Table):
            return {name: _as_array(block.column(name).to_pylist()) for name in block.column_names}
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(f"Cannot interpret {type(block)} as a block")


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if b and BlockAccessor(b).num_rows() > 0]
    if not blocks:
        return {}
    keys = list(blocks[0].keys())
    out: Block = {}
    for k in keys:
        arrays = [b[k] for b in blocks]
        if any(a.dtype == object for a in arrays):
            merged = np.empty(sum(len(a) for a in arrays), dtype=object)
            pos = 0
            for a in arrays:
                merged[pos : pos + len(a)] = a
                pos += len(a)
            out[k] = merged
        else:
            out[k] = np.concatenate(arrays, axis=0)
    return out


def split_block(block: Block, num_splits: int) -> List[Block]:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    bounds = [round(i * n / num_splits) for i in range(num_splits + 1)]
    return [acc.slice(bounds[i], bounds[i + 1]) for i in range(num_splits)]


def _sizeof(v: Any) -> int:
    if isinstance(v, np.ndarray):
        return v.nbytes
    if isinstance(v, (bytes, str)):
        return len(v)
    return 8


def _unbox(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    return v


def _sortable(a: np.ndarray) -> np.ndarray:
    if a.dtype == object:
        return np.asarray([str(x) for x in a])
    return a
