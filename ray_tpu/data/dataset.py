"""Dataset: the lazy distributed dataset API.

Parity: ``python/ray/data/dataset.py`` (the 5.2k-LoC public class) — lazy
logical-plan accumulation, streaming execution on consumption, the full
transform surface (map/map_batches/filter/flat_map/sort/groupby/
repartition/random_shuffle/union/zip/limit), consumption
(take/count/show/iter_*), split/streaming_split, and write connectors.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum, Unique
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata, concat_blocks
from ray_tpu.data.context import DataContext
from ray_tpu.data.executor import RefBundle, StreamingExecutor, plan
from ray_tpu.data.iterator import DataIterator


class Dataset:
    def __init__(self, logical_op: L.LogicalOp):
        self._logical_op = logical_op
        self._last_stats = None

    # ------------------------------------------------------------ plumbing
    def _with(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(op)

    def _execute(self) -> Iterator[RefBundle]:
        ctx = DataContext.get_current()
        optimized = L.optimize(_clone_plan(self._logical_op))
        root = plan(optimized, ctx)
        executor = StreamingExecutor(root, ctx)
        try:
            yield from executor.run()
        finally:
            self._last_stats = executor.stats()
            # observable beyond this handle: the dashboard's Data panel
            # lists recent executions (reference: Data dashboard module)
            from ray_tpu.data.executor import record_execution

            record_execution(L.plan_to_string(optimized).split("\n")[0], self._last_stats)

    def _collect_bundles(self) -> List[RefBundle]:
        return list(self._execute())

    # ---------------------------------------------------------- transforms
    def map(self, fn: Callable, *, num_cpus: float = 1, num_tpus: float = 0, concurrency=None, **kw) -> "Dataset":
        return self._with(
            L.AbstractMap(self._logical_op, "map_rows", fn, num_cpus=num_cpus, num_tpus=num_tpus, concurrency=concurrency)
        )

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute=None,
        fn_args: tuple = (),
        fn_kwargs: Optional[dict] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        num_cpus: float = 1,
        num_tpus: float = 0,
        concurrency=None,
        **kw,
    ) -> "Dataset":
        return self._with(
            L.AbstractMap(
                self._logical_op,
                "map_batches",
                fn,
                fn_args=fn_args,
                fn_kwargs=fn_kwargs,
                batch_size=batch_size,
                batch_format=batch_format,
                compute=compute,
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                concurrency=concurrency,
                fn_constructor_args=fn_constructor_args,
                fn_constructor_kwargs=fn_constructor_kwargs,
            )
        )

    def filter(self, fn: Callable) -> "Dataset":
        return self._with(L.AbstractMap(self._logical_op, "filter", fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with(L.AbstractMap(self._logical_op, "flat_map", fn))

    def add_column(self, name: str, fn: Callable[[Dict[str, np.ndarray]], np.ndarray]) -> "Dataset":
        def add(batch):
            batch[name] = np.asarray(fn(batch))
            return batch

        add.__name__ = f"add_column[{name}]"
        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        drop.__name__ = f"drop_columns[{cols}]"
        return self.map_batches(drop)

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}

        select.__name__ = f"select_columns[{cols}]"
        return self.map_batches(select)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(batch):
            return {mapping.get(k, k): v for k, v in batch.items()}

        rename.__name__ = "rename_columns"
        return self.map_batches(rename)

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(self._logical_op, n))

    def repartition(self, num_blocks: int, *, shuffle: bool = False) -> "Dataset":
        return self._with(L.Repartition(self._logical_op, num_blocks, shuffle))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(L.RandomShuffle(self._logical_op, seed))

    def sort(self, key: Union[str, List[str]], descending: bool = False) -> "Dataset":
        return self._with(L.Sort(self._logical_op, key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(L.Union([self._logical_op] + [o._logical_op for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(L.Zip(self._logical_op, other._logical_op))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    # --------------------------------------------------------- consumption
    def take(self, limit: int = 20) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for bundle in self.limit(limit)._execute():
            for ref in bundle.refs:
                block = ray_tpu.get(ref)
                rows.extend(BlockAccessor(block).iter_rows())
                if len(rows) >= limit:
                    return rows[:limit]
        return rows[:limit]

    def take_all(self) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for bundle in self._execute():
            for ref in bundle.refs:
                rows.extend(BlockAccessor(ray_tpu.get(ref)).iter_rows())
        return rows

    def take_batch(self, batch_size: int = 20, *, batch_format: str = "numpy") -> Any:
        it = self.iterator().iter_batches(batch_size=batch_size, batch_format=batch_format)
        try:
            return next(it)
        except StopIteration:
            return {}

    def count(self) -> int:
        total = 0
        for bundle in self._execute():
            total += bundle.num_rows()
        return total

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def schema(self) -> Optional[Dict[str, Any]]:
        for bundle in self.limit(1)._execute():
            for ref, meta in zip(bundle.refs, bundle.metadata):
                if meta.schema:
                    return meta.schema
                block = ray_tpu.get(ref)
                return BlockAccessor(block).schema()
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        return list(s) if s else None

    def unique(self, column: str) -> List[Any]:
        res = self.groupby(None).aggregate(Unique(column)).take_all()
        if not res:
            return []
        vals = res[0][f"unique({column})"]
        return [v.item() if isinstance(v, np.generic) else v for v in vals]

    def sum(self, on: str):
        return self._global_agg(Sum(on))

    def min(self, on: str):
        return self._global_agg(Min(on))

    def max(self, on: str):
        return self._global_agg(Max(on))

    def mean(self, on: str):
        return self._global_agg(Mean(on))

    def std(self, on: str):
        return self._global_agg(Std(on))

    def _global_agg(self, agg: AggregateFn):
        rows = self.groupby(None).aggregate(agg).take_all()
        return rows[0][agg.name] if rows else None

    def aggregate(self, *aggs: AggregateFn) -> Dict[str, Any]:
        rows = self.groupby(None).aggregate(*aggs).take_all()
        return rows[0] if rows else {}

    # ----------------------------------------------------------- iterators
    def iterator(self) -> DataIterator:
        return DataIterator(self._execute, owner=self)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_jax_batches(**kw)

    def iter_tf_batches(self, **kw) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_tf_batches(**kw)

    def to_tf(self, feature_columns, label_columns, **kw):
        return self.iterator().to_tf(feature_columns, label_columns, **kw)

    def iter_torch_batches(self, **kw) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_torch_batches(**kw)

    # --------------------------------------------------------------- split
    def split(self, n: int, *, locality_hints=None) -> List["MaterializedDataset"]:
        """Materialize and split into n even sub-datasets (parity: split()).

        ``locality_hints`` — one per split: actor handles (the consumer
        actors) or NodeIDs.  Blocks are assigned preferentially to the split
        whose hint node already stores them (parity:
        ``output_splitter.py`` locality_hints /
        ``split.py _split_at_indices`` locality), falling back to greedy
        row-balancing."""
        mat = self.materialize()
        refs = mat._refs
        metas = mat._metadata
        groups: List[List[Tuple[Any, BlockMetadata]]] = [[] for _ in range(n)]
        loads = [0] * n
        hint_nodes = _resolve_locality_hints(locality_hints, n)
        total_rows = sum(max(0, m.num_rows) for m in metas) or 1
        fair_share = 1.3 * total_rows / n
        for ref, meta in sorted(zip(refs, metas), key=lambda rm: -rm[1].num_rows):
            i = None
            if hint_nodes is not None:
                block_nodes = _block_locations(ref)
                # prefer a co-located, not-overloaded split
                candidates = [
                    j for j in range(n)
                    if hint_nodes[j] is not None and hint_nodes[j] in block_nodes
                    and loads[j] + meta.num_rows <= fair_share
                ]
                if candidates:
                    i = min(candidates, key=loads.__getitem__)
            if i is None:
                i = loads.index(min(loads))
            groups[i].append((ref, meta))
            loads[i] += meta.num_rows
        return [MaterializedDataset([r for r, _ in g], [m for _, m in g]) for g in groups]

    def streaming_split(
        self, n: int, *, equal: bool = True, locality_hints=None
    ) -> List[DataIterator]:
        """n coordinated iterators over one execution (parity:
        ``streaming_split`` + OutputSplitter,
        ``_internal/execution/operators/output_splitter.py:1``).  Driver-side
        implementation: one shared executor thread pushes bundles into n
        queues — round-robin when ``equal``; with ``locality_hints`` (one
        actor handle / NodeID per consumer, requires ``equal=False``) each
        bundle prefers the consumer whose node already stores it."""
        import queue as _q
        import threading

        queues: List[_q.Queue] = [_q.Queue(maxsize=4) for _ in range(n)]
        SENTINEL = object()
        hint_nodes = None if equal else _resolve_locality_hints(locality_hints, n)

        def pick_queue(ref, i: int) -> int:
            if hint_nodes is not None:
                block_nodes = _block_locations(ref)
                candidates = [
                    j for j in range(n)
                    if hint_nodes[j] is not None and hint_nodes[j] in block_nodes
                ]
                if candidates:
                    # least-backlogged co-located consumer
                    return min(candidates, key=lambda j: queues[j].qsize())
            return i % n

        def producer():
            i = 0
            for bundle in self._execute():
                for ref, meta in zip(bundle.refs, bundle.metadata):
                    queues[pick_queue(ref, i)].put(RefBundle([ref], [meta]))
                    i += 1
            for q in queues:
                q.put(SENTINEL)

        threading.Thread(target=producer, daemon=True).start()

        def make_iter(q):
            def gen():
                while True:
                    item = q.get()
                    if item is SENTINEL:
                        return
                    yield item

            return DataIterator(gen)

        return [make_iter(q) for q in queues]

    def train_test_split(self, test_size: float, *, shuffle: bool = False, seed: Optional[int] = None):
        ds: Dataset = self.random_shuffle(seed=seed) if shuffle else self
        mat = ds.materialize()
        total = mat.count()
        n_test = int(total * test_size) if isinstance(test_size, float) else test_size
        rows = mat.take_all()
        from ray_tpu.data.read_api import from_items

        return from_items(rows[: total - n_test]), from_items(rows[total - n_test :])

    # --------------------------------------------------------- materialize
    def materialize(self) -> "MaterializedDataset":
        refs, metas = [], []
        for bundle in self._execute():
            refs.extend(bundle.refs)
            metas.extend(bundle.metadata)
        return MaterializedDataset(refs, metas)

    def num_blocks(self) -> int:
        return self.materialize().num_blocks()

    def size_bytes(self) -> int:
        return sum(m.size_bytes for m in self.materialize()._metadata)

    # -------------------------------------------------------------- writes
    def write_csv(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import CSVDatasource

        self._write(CSVDatasource([]), path, kw)

    def write_json(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import JSONDatasource

        self._write(JSONDatasource([]), path, kw)

    def write_numpy(self, path: str, *, column: str = "data", **kw) -> None:
        from ray_tpu.data.datasource import NumpyDatasource

        kw["column"] = column
        self._write(NumpyDatasource([]), path, kw)

    def write_parquet(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import ParquetDatasource

        self._write(ParquetDatasource([]), path, kw)

    def write_tfrecords(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import TFRecordDatasource

        self._write(TFRecordDatasource([]), path, kw)

    def write_delta(self, table_path: str, *, mode: str = "append") -> None:
        """Delta Lake commit (parquet part files + _delta_log JSON commit;
        mode: append | overwrite)."""
        from ray_tpu.data.datasource_lakes import DeltaWriteDatasource

        self._write(DeltaWriteDatasource(mode), table_path, {})

    def write_lance(self, uri: str, *, mode: str = "create") -> None:
        """Lance dataset (requires the lance package)."""
        from ray_tpu.data.datasource_lakes import LanceWriteDatasource

        self._write(LanceWriteDatasource(mode), uri, {})

    def write_sql(self, table: str, connection_factory, *, paramstyle: str = "qmark") -> None:
        """Insert all rows into a DB table via DB-API (parity: write_sql)."""
        from ray_tpu.data.datasource import SQLDatasource

        self._write(
            SQLDatasource("", connection_factory), table, {"paramstyle": paramstyle}
        )

    def _write(self, datasource, path: str, kw: dict) -> None:
        sink = Dataset(L.Write(self._logical_op, datasource, path, kw))
        for _ in sink._execute():
            pass

    # --------------------------------------------------------------- misc
    def to_pandas(self):
        mat = self.materialize()
        blocks = [ray_tpu.get(r) for r in mat._refs]
        merged = concat_blocks([b for b in blocks if b])
        return BlockAccessor(merged).to_pandas()

    def stats(self) -> str:
        if self._last_stats is None:
            return "(dataset not yet executed)"
        return self._last_stats.summary()

    def __repr__(self) -> str:
        return f"Dataset(plan=\n{L.plan_to_string(self._logical_op)}\n)"


class MaterializedDataset(Dataset):
    """A Dataset whose blocks are already in the object store
    (parity: MaterializedDataset)."""

    def __init__(self, refs: List[Any], metadata: List[BlockMetadata]):
        super().__init__(L.InputData(refs, metadata))
        self._refs = refs
        self._metadata = metadata

    def num_blocks(self) -> int:
        return len(self._refs)

    def count(self) -> int:
        return sum(m.num_rows for m in self._metadata)

    def materialize(self) -> "MaterializedDataset":
        return self


class GroupedData:
    """Result of ``Dataset.groupby`` (parity: grouped_data.py)."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return Dataset(L.Aggregate(self._ds._logical_op, self._key, list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: str) -> Dataset:
        return self.aggregate(Std(on))

    def map_groups(self, fn: Callable[[Block], Any]) -> Dataset:
        """Apply fn to each group (materializing implementation)."""
        key = self._key
        sorted_ds = self._ds.sort(key)

        def apply_groups(batch: Block) -> Block:
            from ray_tpu.data.block import _sortable, block_from_rows

            acc = BlockAccessor(batch)
            if not batch or not acc.num_rows():
                return {}
            col = _sortable(batch[key])
            change = np.nonzero(col[1:] != col[:-1])[0] + 1
            starts = np.concatenate([[0], change])
            ends = np.concatenate([change, [len(col)]])
            outs = []
            for s, e in zip(starts, ends):
                res = fn(acc.slice(int(s), int(e)))
                outs.append(normalize_or_rows(res))
            return concat_blocks(outs)

        apply_groups.__name__ = f"map_groups[{getattr(fn, '__name__', 'fn')}]"
        return sorted_ds.map_batches(apply_groups, batch_size=None)


def normalize_or_rows(res: Any) -> Block:
    from ray_tpu.data.block import block_from_rows, normalize_block

    if isinstance(res, list):
        return block_from_rows(res)
    if isinstance(res, dict) and res and not any(hasattr(v, "__len__") for v in res.values()):
        return block_from_rows([res])
    return normalize_block(res)


def _clone_plan(op: L.LogicalOp) -> L.LogicalOp:
    """Shallow-clone the logical DAG so optimization never mutates the
    user-held plan (Datasets are immutable/reusable)."""
    import copy

    cloned = copy.copy(op)
    cloned.inputs = [_clone_plan(i) for i in op.inputs]
    if isinstance(cloned, L.FusedMap):
        cloned.stages = list(cloned.stages)
    return cloned


def _resolve_locality_hints(hints, n: int):
    """Resolve split locality hints (actor handles or NodeIDs) to NodeIDs.
    Returns None when no usable hints (plain balanced split)."""
    if not hints:
        return None
    if len(hints) != n:
        raise ValueError(f"locality_hints must have length {n}, got {len(hints)}")
    from ray_tpu.core.ids import NodeID

    cluster = ray_tpu.get_cluster()
    nodes = []
    for h in hints:
        node_id = None
        if isinstance(h, NodeID):
            node_id = h
        else:
            actor_id = getattr(h, "_actor_id", None)
            if actor_id is not None:
                info = cluster.control.actors.get(actor_id)
                if info is not None:
                    node_id = info.node_id
        nodes.append(node_id)
    return nodes if any(x is not None for x in nodes) else None


def _block_locations(ref) -> set:
    try:
        return ray_tpu.get_cluster().directory.locations(ref.id())
    except Exception:  # noqa: BLE001
        return set()
