"""Dataset: the lazy distributed dataset API.

Parity: ``python/ray/data/dataset.py`` (the 5.2k-LoC public class) — lazy
logical-plan accumulation, streaming execution on consumption, the full
transform surface (map/map_batches/filter/flat_map/sort/groupby/
repartition/random_shuffle/union/zip/limit), consumption
(take/count/show/iter_*), split/streaming_split, and write connectors.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum, Unique
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata, concat_blocks
from ray_tpu.data.context import DataContext
from ray_tpu.data.executor import RefBundle, StreamingExecutor, plan
from ray_tpu.data.iterator import DataIterator


class Dataset:
    def __init__(self, logical_op: L.LogicalOp):
        self._logical_op = logical_op
        self._last_stats = None

    # ------------------------------------------------------------ plumbing
    def _with(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(op)

    def _execute(self, preserve_order: Optional[bool] = None) -> Iterator[RefBundle]:
        """``preserve_order=True`` forces dispatch-order output release for
        THIS execution regardless of the context default — row-positional
        consumers (``take``/``take_all``) need it: without it parallel map
        tasks finishing out of order make ``take(1)`` return an arbitrary
        block's rows (a long-standing flake; blocks completed in order only
        by timing luck)."""
        ctx = DataContext.get_current()
        restore = None
        if preserve_order is not None and ctx.preserve_order != preserve_order:
            restore = ctx.preserve_order
            ctx.preserve_order = preserve_order
        try:
            optimized = L.optimize(_clone_plan(self._logical_op))
            root = plan(optimized, ctx)
            executor = StreamingExecutor(root, ctx)
        finally:
            # operators capture the flag at construction: the context can
            # restore as soon as the physical plan exists
            if restore is not None:
                ctx.preserve_order = restore
        try:
            yield from executor.run()
        finally:
            self._last_stats = executor.stats()
            # observable beyond this handle: the dashboard's Data panel
            # lists recent executions (reference: Data dashboard module)
            from ray_tpu.data.executor import record_execution

            record_execution(L.plan_to_string(optimized).split("\n")[0], self._last_stats)

    def _collect_bundles(self) -> List[RefBundle]:
        return list(self._execute())

    # ---------------------------------------------------------- transforms
    def map(self, fn: Callable, *, num_cpus: float = 1, num_tpus: float = 0, concurrency=None, **kw) -> "Dataset":
        return self._with(
            L.AbstractMap(self._logical_op, "map_rows", fn, num_cpus=num_cpus, num_tpus=num_tpus, concurrency=concurrency)
        )

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute=None,
        fn_args: tuple = (),
        fn_kwargs: Optional[dict] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        num_cpus: float = 1,
        num_tpus: float = 0,
        concurrency=None,
        **kw,
    ) -> "Dataset":
        return self._with(
            L.AbstractMap(
                self._logical_op,
                "map_batches",
                fn,
                fn_args=fn_args,
                fn_kwargs=fn_kwargs,
                batch_size=batch_size,
                batch_format=batch_format,
                compute=compute,
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                concurrency=concurrency,
                fn_constructor_args=fn_constructor_args,
                fn_constructor_kwargs=fn_constructor_kwargs,
            )
        )

    def filter(self, fn: Callable) -> "Dataset":
        return self._with(L.AbstractMap(self._logical_op, "filter", fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with(L.AbstractMap(self._logical_op, "flat_map", fn))

    def add_column(self, name: str, fn: Callable[[Dict[str, np.ndarray]], np.ndarray]) -> "Dataset":
        def add(batch):
            batch[name] = np.asarray(fn(batch))
            return batch

        add.__name__ = f"add_column[{name}]"
        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        drop.__name__ = f"drop_columns[{cols}]"
        return self.map_batches(drop)

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}

        select.__name__ = f"select_columns[{cols}]"
        return self.map_batches(select)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(batch):
            return {mapping.get(k, k): v for k, v in batch.items()}

        rename.__name__ = "rename_columns"
        return self.map_batches(rename)

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(self._logical_op, n))

    def repartition(self, num_blocks: int, *, shuffle: bool = False) -> "Dataset":
        return self._with(L.Repartition(self._logical_op, num_blocks, shuffle))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(L.RandomShuffle(self._logical_op, seed))

    def sort(self, key: Union[str, List[str]], descending: bool = False) -> "Dataset":
        return self._with(L.Sort(self._logical_op, key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(L.Union([self._logical_op] + [o._logical_op for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(L.Zip(self._logical_op, other._logical_op))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    # --------------------------------------------------------- consumption
    def take(self, limit: int = 20) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        # row-positional by definition: "the first N rows" only means
        # something in dispatch order (see _execute docstring)
        for bundle in self.limit(limit)._execute(preserve_order=True):
            for ref in bundle.refs:
                block = ray_tpu.get(ref)
                rows.extend(BlockAccessor(block).iter_rows())
                if len(rows) >= limit:
                    return rows[:limit]
        return rows[:limit]

    def take_all(self) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for bundle in self._execute(preserve_order=True):
            for ref in bundle.refs:
                rows.extend(BlockAccessor(ray_tpu.get(ref)).iter_rows())
        return rows

    def take_batch(self, batch_size: int = 20, *, batch_format: str = "numpy") -> Any:
        it = self.iterator().iter_batches(batch_size=batch_size, batch_format=batch_format)
        try:
            return next(it)
        except StopIteration:
            return {}

    def count(self) -> int:
        total = 0
        for bundle in self._execute():
            total += bundle.num_rows()
        return total

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def schema(self) -> Optional["Schema"]:
        from ray_tpu.data.compute import Schema

        for bundle in self.limit(1)._execute():
            for ref, meta in zip(bundle.refs, bundle.metadata):
                if meta.schema:
                    return Schema(meta.schema)
                block = ray_tpu.get(ref)
                s = BlockAccessor(block).schema()
                return Schema(s) if s is not None else None
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        return list(s) if s else None

    def unique(self, column: str) -> List[Any]:
        res = self.groupby(None).aggregate(Unique(column)).take_all()
        if not res:
            return []
        vals = res[0][f"unique({column})"]
        return [v.item() if isinstance(v, np.generic) else v for v in vals]

    def sum(self, on: str):
        return self._global_agg(Sum(on))

    def min(self, on: str):
        return self._global_agg(Min(on))

    def max(self, on: str):
        return self._global_agg(Max(on))

    def mean(self, on: str):
        return self._global_agg(Mean(on))

    def std(self, on: str):
        return self._global_agg(Std(on))

    def _global_agg(self, agg: AggregateFn):
        rows = self.groupby(None).aggregate(agg).take_all()
        return rows[0][agg.name] if rows else None

    def aggregate(self, *aggs: AggregateFn) -> Dict[str, Any]:
        rows = self.groupby(None).aggregate(*aggs).take_all()
        return rows[0] if rows else {}

    # ----------------------------------------------------------- iterators
    def iterator(self) -> DataIterator:
        return DataIterator(self._execute, owner=self)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_jax_batches(**kw)

    def iter_tf_batches(self, **kw) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_tf_batches(**kw)

    def to_tf(self, feature_columns, label_columns, **kw):
        return self.iterator().to_tf(feature_columns, label_columns, **kw)

    def iter_torch_batches(self, **kw) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_torch_batches(**kw)

    # --------------------------------------------------------------- split
    def split(self, n: int, *, locality_hints=None) -> List["MaterializedDataset"]:
        """Materialize and split into n even sub-datasets (parity: split()).

        ``locality_hints`` — one per split: actor handles (the consumer
        actors) or NodeIDs.  Blocks are assigned preferentially to the split
        whose hint node already stores them (parity:
        ``output_splitter.py`` locality_hints /
        ``split.py _split_at_indices`` locality), falling back to greedy
        row-balancing."""
        mat = self.materialize()
        refs = mat._refs
        metas = mat._metadata
        groups: List[List[Tuple[Any, BlockMetadata]]] = [[] for _ in range(n)]
        loads = [0] * n
        hint_nodes = _resolve_locality_hints(locality_hints, n)
        total_rows = sum(max(0, m.num_rows) for m in metas) or 1
        fair_share = 1.3 * total_rows / n
        for ref, meta in sorted(zip(refs, metas), key=lambda rm: -rm[1].num_rows):
            i = None
            if hint_nodes is not None:
                block_nodes = _block_locations(ref)
                # prefer a co-located, not-overloaded split
                candidates = [
                    j for j in range(n)
                    if hint_nodes[j] is not None and hint_nodes[j] in block_nodes
                    and loads[j] + meta.num_rows <= fair_share
                ]
                if candidates:
                    i = min(candidates, key=loads.__getitem__)
            if i is None:
                i = loads.index(min(loads))
            groups[i].append((ref, meta))
            loads[i] += meta.num_rows
        return [MaterializedDataset([r for r, _ in g], [m for _, m in g]) for g in groups]

    def streaming_split(
        self, n: int, *, equal: bool = True, locality_hints=None
    ) -> List[DataIterator]:
        """n coordinated iterators over one execution (parity:
        ``streaming_split`` + OutputSplitter,
        ``_internal/execution/operators/output_splitter.py:1``).  Driver-side
        implementation: one shared executor thread pushes bundles into n
        queues — round-robin when ``equal``; with ``locality_hints`` (one
        actor handle / NodeID per consumer, requires ``equal=False``) each
        bundle prefers the consumer whose node already stores it."""
        import queue as _q
        import threading

        queues: List[_q.Queue] = [_q.Queue(maxsize=4) for _ in range(n)]
        SENTINEL = object()
        hint_nodes = None if equal else _resolve_locality_hints(locality_hints, n)

        def pick_queue(ref, i: int) -> int:
            if hint_nodes is not None:
                block_nodes = _block_locations(ref)
                candidates = [
                    j for j in range(n)
                    if hint_nodes[j] is not None and hint_nodes[j] in block_nodes
                ]
                if candidates:
                    # least-backlogged co-located consumer
                    return min(candidates, key=lambda j: queues[j].qsize())
            return i % n

        def producer():
            i = 0
            for bundle in self._execute():
                for ref, meta in zip(bundle.refs, bundle.metadata):
                    queues[pick_queue(ref, i)].put(RefBundle([ref], [meta]))
                    i += 1
            for q in queues:
                q.put(SENTINEL)

        threading.Thread(target=producer, daemon=True).start()

        def make_iter(q):
            def gen():
                while True:
                    item = q.get()
                    if item is SENTINEL:
                        return
                    yield item

            return DataIterator(gen)

        return [make_iter(q) for q in queues]

    def train_test_split(self, test_size: float, *, shuffle: bool = False, seed: Optional[int] = None):
        ds: Dataset = self.random_shuffle(seed=seed) if shuffle else self
        mat = ds.materialize()
        total = mat.count()
        n_test = int(total * test_size) if isinstance(test_size, float) else test_size
        rows = mat.take_all()
        from ray_tpu.data.read_api import from_items

        return from_items(rows[: total - n_test]), from_items(rows[total - n_test :])

    # --------------------------------------------------------- materialize
    def materialize(self) -> "MaterializedDataset":
        refs, metas = [], []
        for bundle in self._execute():
            refs.extend(bundle.refs)
            metas.extend(bundle.metadata)
        return MaterializedDataset(refs, metas)

    def num_blocks(self) -> int:
        return self.materialize().num_blocks()

    def size_bytes(self) -> int:
        return sum(m.size_bytes for m in self.materialize()._metadata)

    # -------------------------------------------------------------- writes
    def write_csv(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import CSVDatasource

        self._write(CSVDatasource([]), path, kw)

    def write_json(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import JSONDatasource

        self._write(JSONDatasource([]), path, kw)

    def write_numpy(self, path: str, *, column: str = "data", **kw) -> None:
        from ray_tpu.data.datasource import NumpyDatasource

        kw["column"] = column
        self._write(NumpyDatasource([]), path, kw)

    def write_parquet(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import ParquetDatasource

        self._write(ParquetDatasource([]), path, kw)

    def write_tfrecords(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import TFRecordDatasource

        self._write(TFRecordDatasource([]), path, kw)

    def write_delta(self, table_path: str, *, mode: str = "append") -> None:
        """Delta Lake commit (parquet part files + _delta_log JSON commit;
        mode: append | overwrite)."""
        from ray_tpu.data.datasource_lakes import DeltaWriteDatasource

        self._write(DeltaWriteDatasource(mode), table_path, {})

    def write_lance(self, uri: str, *, mode: str = "create") -> None:
        """Lance dataset (requires the lance package)."""
        from ray_tpu.data.datasource_lakes import LanceWriteDatasource

        self._write(LanceWriteDatasource(mode), uri, {})

    def write_sql(self, table: str, connection_factory, *, paramstyle: str = "qmark") -> None:
        """Insert all rows into a DB table via DB-API (parity: write_sql)."""
        from ray_tpu.data.datasource import SQLDatasource

        self._write(
            SQLDatasource("", connection_factory), table, {"paramstyle": paramstyle}
        )

    def _write(self, datasource, path: str, kw: dict) -> None:
        sink = Dataset(L.Write(self._logical_op, datasource, path, kw))
        for _ in sink._execute():
            pass

    # --------------------------------------------------------------- misc
    def to_pandas(self):
        mat = self.materialize()
        blocks = [ray_tpu.get(r) for r in mat._refs]
        merged = concat_blocks([b for b in blocks if b])
        return BlockAccessor(merged).to_pandas()

    # ------------------------------------------------- metadata (parity:
    # dataset.py context/copy/names/types/input_files)
    def context(self) -> DataContext:
        return DataContext.get_current()

    def copy(self) -> "Dataset":
        return Dataset(_clone_plan(self._logical_op))

    def names(self) -> Optional[List[str]]:
        return self.columns()

    def types(self) -> Optional[List[Any]]:
        s = self.schema()
        return list(s.values()) if s else None

    def input_files(self) -> List[str]:
        """Every file path feeding the plan's Read leaves."""
        files: List[str] = []

        def walk(op):
            for i in op.inputs:
                walk(i)
            if isinstance(op, L.Read):
                files.extend(getattr(op.datasource, "paths", []) or [])

        walk(self._logical_op)
        return files

    # ---------------------------------------------- sampling / block order
    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        """Keep each row independently with probability ``fraction``.  With a
        seed, the mask is derived from (seed, block contents) so re-running
        the plan reproduces the sample without coordinating block indices
        across tasks."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")

        def sample(batch):
            import zlib

            n = len(next(iter(batch.values()))) if batch else 0
            if n == 0:
                return batch
            if seed is None:
                rng = np.random.default_rng()
            else:
                # crc32, not hash(): the fn runs in worker processes, where
                # Python's salted hash() differs per process and would break
                # the seeded-reproducibility contract on retries/re-runs.
                # The digest covers EVERY column's full bytes — a prefix of
                # the first column would give equal-size blocks sharing a
                # constant lead column the identical keep-mask (correlated,
                # non-uniform sampling)
                digest = seed ^ n
                for key in sorted(batch):
                    arr = np.ascontiguousarray(np.asarray(batch[key]))
                    if arr.dtype != object:
                        digest = zlib.crc32(arr.tobytes(), digest)
                    else:
                        # per-element: no monolithic repr of the whole
                        # column just to feed the checksum
                        for item in arr.flat:
                            digest = zlib.crc32(str(item).encode(), digest)
                rng = np.random.default_rng(digest & 0x7FFFFFFF)
            mask = rng.random(n) < fraction
            return {k: np.asarray(v)[mask] for k, v in batch.items()}

        sample.__name__ = f"random_sample[{fraction}]"
        return self.map_batches(sample)

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        """Shuffle WHOLE blocks (cheap coarse shuffle — no row movement).
        Executes the plan; the result is a materialized dataset with its
        block list permuted (parity: randomize_block_order)."""
        mat = self.materialize()
        order = np.random.default_rng(seed).permutation(len(mat._refs))
        return MaterializedDataset(
            [mat._refs[i] for i in order], [mat._metadata[i] for i in order]
        )

    # ------------------------------------------------------ indexed splits
    def split_at_indices(self, indices: List[int]) -> List["MaterializedDataset"]:
        """Split into len(indices)+1 datasets at the given GLOBAL row
        offsets (parity: split_at_indices; boundary blocks are sliced by a
        remote task, interior blocks move by reference)."""
        if any(i < 0 for i in indices) or list(indices) != sorted(indices):
            raise ValueError("indices must be non-negative and sorted")
        mat = self.materialize()

        @ray_tpu.remote
        def slice_block(block, start: int, end: int):
            return BlockAccessor(block).slice(start, end)

        bounds = list(indices) + [None]  # None = rest
        out: List[MaterializedDataset] = []
        blocks = list(zip(mat._refs, mat._metadata))
        bi = 0            # current block index
        row_in_block = 0  # rows of blocks[bi] already consumed
        global_row = 0
        for bound in bounds:
            refs: List[Any] = []
            metas: List[BlockMetadata] = []
            while bi < len(blocks):
                ref, meta = blocks[bi]
                n = meta.num_rows
                remaining = n - row_in_block
                if bound is None or global_row + remaining <= bound:
                    # whole (rest of) block belongs to this split
                    if row_in_block == 0:
                        refs.append(ref)
                        metas.append(meta)
                    elif remaining > 0:
                        sliced = slice_block.remote(ref, row_in_block, n)
                        refs.append(sliced)
                        metas.append(BlockMetadata(num_rows=remaining, size_bytes=0, schema=meta.schema))
                    global_row += remaining
                    bi += 1
                    row_in_block = 0
                    if bound is not None and global_row == bound:
                        break
                else:
                    take = bound - global_row
                    if take > 0:
                        sliced = slice_block.remote(ref, row_in_block, row_in_block + take)
                        refs.append(sliced)
                        metas.append(BlockMetadata(num_rows=take, size_bytes=0, schema=meta.schema))
                        row_in_block += take
                        global_row = bound
                    break
            out.append(MaterializedDataset(refs, metas))
        return out

    def split_proportionately(self, proportions: List[float]) -> List["MaterializedDataset"]:
        """Split by fractions; a final split receives the remainder
        (parity: split_proportionately)."""
        if not proportions or any(p <= 0 for p in proportions) or sum(proportions) >= 1.0:
            raise ValueError("proportions must be positive and sum to < 1")
        # materialize ONCE: count and the split must see the same execution
        # (a second run would double the cost and can disagree on the total
        # when an upstream op is nondeterministic)
        mat = self.materialize()
        total = mat.count()
        indices = []
        acc = 0
        for p in proportions:
            acc += int(total * p)
            indices.append(acc)
        return mat.split_at_indices(indices)

    # -------------------------------------------- refs-based consumption
    def get_internal_block_refs(self) -> List[Any]:
        return self.materialize()._refs

    def to_numpy_refs(self, *, column: Optional[str] = None) -> List[Any]:
        """One ref per block: dict of numpy arrays (or one array when
        ``column`` is given)."""

        @ray_tpu.remote
        def to_np(block):
            return BlockAccessor(block).to_numpy(column)

        return [to_np.remote(r) for r in self.materialize()._refs]

    def to_pandas_refs(self) -> List[Any]:
        @ray_tpu.remote
        def to_pd(block):
            return BlockAccessor(block).to_pandas()

        return [to_pd.remote(r) for r in self.materialize()._refs]

    def to_arrow_refs(self) -> List[Any]:
        @ray_tpu.remote
        def to_arrow(block):
            return BlockAccessor(block).to_arrow()

        return [to_arrow.remote(r) for r in self.materialize()._refs]

    def to_torch(self, **kwargs):
        """A ``torch.utils.data.IterableDataset`` yielding
        ``(features, label)`` tensor pairs (label None when no
        ``label_column``) — parity: Dataset.to_torch.  Delegates to
        :meth:`DataIterator.to_torch` so both entry points share one
        implementation (dtype handling, dict feature groups, prefetch).
        Reference semantics: column dtypes are PRESERVED (cast explicitly
        via ``feature_column_dtypes``/``label_column_dtype``) and the label
        unsqueezes to ``[B, 1]`` unless ``unsqueeze_label_tensor=False``."""
        return self.iterator().to_torch(**kwargs)

    def to_random_access_dataset(self, key: str, *, num_workers: int = 4):
        """Serve this dataset for random key lookups from a pool of actors
        (parity: random_access_dataset.py)."""
        from ray_tpu.data.random_access import RandomAccessDataset

        return RandomAccessDataset(self, key, num_workers=num_workers)

    # ------------------------------------------------------------ lineage
    def has_serializable_lineage(self) -> bool:
        """True when the plan can be pickled and re-executed elsewhere —
        i.e. every leaf is a Read (InputData holds process-local refs)."""

        def ok(op) -> bool:
            if isinstance(op, L.InputData):
                return False
            return all(ok(i) for i in op.inputs)

        return ok(self._logical_op)

    def serialize_lineage(self) -> bytes:
        if not self.has_serializable_lineage():
            raise ValueError(
                "dataset lineage is not serializable: the plan contains "
                "materialized InputData blocks (only Read-rooted plans can "
                "be re-executed elsewhere)"
            )
        import cloudpickle

        return cloudpickle.dumps(_clone_plan(self._logical_op))

    @staticmethod
    def deserialize_lineage(blob: bytes) -> "Dataset":
        import pickle

        return Dataset(pickle.loads(blob))

    # ------------------------------------------------------ write tail
    def write_images(self, path: str, column: str = "image", *, file_format: str = "png", **kw) -> None:
        from ray_tpu.data.datasource import ImageWriteDatasource

        kw.update({"column": column, "file_format": file_format})
        self._write(ImageWriteDatasource([]), path, kw)

    def write_webdataset(self, path: str, **kw) -> None:
        from ray_tpu.data.datasource import WebDatasetWriteDatasource

        self._write(WebDatasetWriteDatasource([]), path, kw)

    def write_datasource(self, datasource, *, path: str = "", **write_args) -> None:
        """Write through any Datasource with a ``write_block`` /
        ``write`` surface (parity: write_datasource)."""
        self._write(datasource, path, write_args)

    # reference 2.9 renamed Datasource->Datasink on the write path; both
    # spellings accept the same object here
    write_datasink = write_datasource

    def write_mongo(self, uri: str, database: str, collection: str, **kw) -> None:
        raise ImportError(
            "write_mongo requires the pymongo package, which is not "
            "installed in this environment; write_json + a mongoimport "
            "step, or write_sql against a DB-API driver, are the native "
            "alternatives"
        )

    def write_bigquery(self, project_id: str, dataset: str, **kw) -> None:
        raise ImportError(
            "write_bigquery requires google-cloud-bigquery, which is not "
            "installed in this environment; write_parquet to GCS + a "
            "BigQuery load job is the native alternative"
        )

    # --------------------------------------- external-frame interop (gated)
    def to_dask(self):
        raise ImportError(
            "to_dask requires the dask package, which is not installed; "
            "to_pandas()/to_pandas_refs() or iter_batches() are the native "
            "consumption paths"
        )

    def to_mars(self):
        raise ImportError("to_mars requires the mars package, which is not installed")

    def to_modin(self):
        raise ImportError("to_modin requires the modin package, which is not installed")

    def to_spark(self, spark=None):
        raise ImportError(
            "to_spark requires pyspark, which is not installed; "
            "write_parquet + spark.read.parquet is the native alternative"
        )

    def stats(self) -> str:
        if self._last_stats is None:
            return "(dataset not yet executed)"
        return self._last_stats.summary()

    def __repr__(self) -> str:
        return f"Dataset(plan=\n{L.plan_to_string(self._logical_op)}\n)"


class MaterializedDataset(Dataset):
    """A Dataset whose blocks are already in the object store
    (parity: MaterializedDataset)."""

    def __init__(self, refs: List[Any], metadata: List[BlockMetadata]):
        super().__init__(L.InputData(refs, metadata))
        self._refs = refs
        self._metadata = metadata

    def num_blocks(self) -> int:
        return len(self._refs)

    def count(self) -> int:
        return sum(m.num_rows for m in self._metadata)

    def materialize(self) -> "MaterializedDataset":
        return self


class GroupedData:
    """Result of ``Dataset.groupby`` (parity: grouped_data.py)."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return Dataset(L.Aggregate(self._ds._logical_op, self._key, list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: str) -> Dataset:
        return self.aggregate(Std(on))

    def map_groups(self, fn: Callable[[Block], Any]) -> Dataset:
        """Apply fn to each group (materializing implementation)."""
        key = self._key
        sorted_ds = self._ds.sort(key)

        def apply_groups(batch: Block) -> Block:
            from ray_tpu.data.block import _sortable, block_from_rows

            acc = BlockAccessor(batch)
            if not batch or not acc.num_rows():
                return {}
            col = _sortable(batch[key])
            change = np.nonzero(col[1:] != col[:-1])[0] + 1
            starts = np.concatenate([[0], change])
            ends = np.concatenate([change, [len(col)]])
            outs = []
            for s, e in zip(starts, ends):
                res = fn(acc.slice(int(s), int(e)))
                outs.append(normalize_or_rows(res))
            return concat_blocks(outs)

        apply_groups.__name__ = f"map_groups[{getattr(fn, '__name__', 'fn')}]"
        return sorted_ds.map_batches(apply_groups, batch_size=None)


def normalize_or_rows(res: Any) -> Block:
    from ray_tpu.data.block import block_from_rows, normalize_block

    if isinstance(res, list):
        return block_from_rows(res)
    if isinstance(res, dict) and res and not any(hasattr(v, "__len__") for v in res.values()):
        return block_from_rows([res])
    return normalize_block(res)


def _clone_plan(op: L.LogicalOp) -> L.LogicalOp:
    """Shallow-clone the logical DAG so optimization never mutates the
    user-held plan (Datasets are immutable/reusable)."""
    import copy

    cloned = copy.copy(op)
    cloned.inputs = [_clone_plan(i) for i in op.inputs]
    if isinstance(cloned, L.FusedMap):
        cloned.stages = list(cloned.stages)
    return cloned


def _resolve_locality_hints(hints, n: int):
    """Resolve split locality hints (actor handles or NodeIDs) to NodeIDs.
    Returns None when no usable hints (plain balanced split)."""
    if not hints:
        return None
    if len(hints) != n:
        raise ValueError(f"locality_hints must have length {n}, got {len(hints)}")
    from ray_tpu.core.ids import NodeID

    cluster = ray_tpu.get_cluster()
    nodes = []
    for h in hints:
        node_id = None
        if isinstance(h, NodeID):
            node_id = h
        else:
            actor_id = getattr(h, "_actor_id", None)
            if actor_id is not None:
                info = cluster.control.actors.get(actor_id)
                if info is not None:
                    node_id = info.node_id
        nodes.append(node_id)
    return nodes if any(x is not None for x in nodes) else None


def _block_locations(ref) -> set:
    try:
        return ray_tpu.get_cluster().directory.locations(ref.id())
    except Exception:  # noqa: BLE001
        return set()
