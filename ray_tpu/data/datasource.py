"""Datasources: pluggable read/write connectors producing ReadTasks.

Parity: ``python/ray/data/datasource/`` (Datasource/Reader/ReadTask model —
each ReadTask is a serializable thunk run as a remote task that yields
blocks) and ``read_api.py``'s family of ``read_*`` constructors.
"""

from __future__ import annotations

import glob as _glob
import json as _json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import (
    ITEM_COLUMN,
    Block,
    BlockAccessor,
    BlockMetadata,
    block_from_items,
    block_from_rows,
)


@dataclass
class ReadTask:
    """A serializable unit of reading: ``fn()`` yields one or more blocks.

    Parity: ``python/ray/data/datasource/datasource.py`` ReadTask — carries
    metadata estimates so the planner can size the read stage without
    executing it.
    """

    fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata

    def __call__(self) -> Iterable[Block]:
        return self.fn()


class Datasource:
    """Base connector interface (parity: datasource.py Datasource)."""

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "")

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def write(self, blocks: List[Block], path: str, **kwargs) -> None:
        raise NotImplementedError


# --------------------------------------------------------------------------
# Write-side bases (parity: the 2.9+ Datasink split — datasink.py,
# _internal/datasource/*_datasink.py). Dataset.write_datasink accepts any of
# these; the file sinks write one part file per block through an open
# binary stream, so subclasses only format rows/blocks.
# --------------------------------------------------------------------------
class Datasink:
    """Write-connector base (parity: ray.data.Datasink)."""

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasink", "")

    def on_write_start(self) -> None:
        pass

    def write(self, blocks: List[Block], path: str, **kwargs) -> None:
        raise NotImplementedError

    def on_write_complete(self) -> None:
        pass


class _FileDatasink(Datasink):
    def __init__(self, file_extension: str = "out"):
        self.file_extension = file_extension

    def write(self, blocks: List[Block], path: str, **kwargs) -> None:
        os.makedirs(path, exist_ok=True)
        self.on_write_start()
        for i, block in enumerate(blocks):
            fname = os.path.join(path, f"part-{i:05d}.{self.file_extension}")
            with open(fname, "wb") as f:
                self._write_one(block, f)
        self.on_write_complete()

    def _write_one(self, block: Block, file) -> None:
        raise NotImplementedError


class BlockBasedFileDatasink(_FileDatasink):
    """Subclass and implement ``write_block_to_file(block, file)``
    (parity: ray.data.BlockBasedFileDatasink)."""

    def _write_one(self, block: Block, file) -> None:
        self.write_block_to_file(block, file)

    def write_block_to_file(self, block: Block, file) -> None:
        raise NotImplementedError


class RowBasedFileDatasink(_FileDatasink):
    """Subclass and implement ``write_row_to_file(row, file)`` — called once
    per row of each block (parity: ray.data.RowBasedFileDatasink)."""

    def _write_one(self, block: Block, file) -> None:
        for row in BlockAccessor(block).iter_rows():
            self.write_row_to_file(row, file)

    def write_row_to_file(self, row: dict, file) -> None:
        raise NotImplementedError


# --------------------------------------------------------------------------
# In-memory sources
# --------------------------------------------------------------------------
class RangeDatasource(Datasource):
    """``range``/``range_tensor`` source (parity: read_api.py range())."""

    def __init__(self, n: int, tensor_shape: Optional[tuple] = None):
        self.n = n
        self.tensor_shape = tensor_shape

    def estimate_inmemory_data_size(self) -> Optional[int]:
        per_row = 8 * int(np.prod(self.tensor_shape)) if self.tensor_shape else 8
        return self.n * per_row

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        tasks = []
        bounds = [round(i * self.n / parallelism) for i in range(parallelism + 1)]
        for i in range(parallelism):
            lo, hi = bounds[i], bounds[i + 1]
            shape = self.tensor_shape

            def make(lo=lo, hi=hi, shape=shape):
                if shape:
                    base = np.arange(lo, hi, dtype=np.int64).reshape((-1,) + (1,) * len(shape))
                    yield {"data": np.broadcast_to(base, (hi - lo,) + tuple(shape)).copy()}
                else:
                    yield {"id": np.arange(lo, hi, dtype=np.int64)}

            per_row = 8 * int(np.prod(shape)) if shape else 8
            meta = BlockMetadata(num_rows=hi - lo, size_bytes=(hi - lo) * per_row)
            tasks.append(ReadTask(make, meta))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = items

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self.items)
        parallelism = max(1, min(parallelism, n or 1))
        bounds = [round(i * n / parallelism) for i in range(parallelism + 1)]
        tasks = []
        for i in range(parallelism):
            chunk = self.items[bounds[i] : bounds[i + 1]]

            def make(chunk=chunk):
                yield block_from_items(chunk)

            meta = BlockMetadata(num_rows=len(chunk), size_bytes=len(chunk) * 8)
            tasks.append(ReadTask(make, meta))
        return tasks


class BlocksDatasource(Datasource):
    """Wraps already-materialized blocks (from_numpy/from_pandas/from_arrow)."""

    def __init__(self, blocks: List[Block]):
        self.blocks = blocks

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for b in self.blocks:
            acc = BlockAccessor.for_block(b)

            def make(b=acc.to_block()):
                yield b

            tasks.append(ReadTask(make, acc.get_metadata()))
        return tasks


# --------------------------------------------------------------------------
# File-based sources
# --------------------------------------------------------------------------
def _expand_paths(paths, metadata_prefixes: tuple = ()) -> List[str]:
    """Directories expand RECURSIVELY to files (partitioned layouts nest
    data under key=value / bucket subdirectories).  Dotfiles are skipped
    (glob's historical behavior); ``metadata_prefixes`` lets parquet-family
    sources additionally skip their _-prefixed metadata entries
    (_delta_log, _partition_spec.json) without hiding underscore-named
    data files from text/csv/json readers."""
    if isinstance(paths, str):
        paths = [paths]
    skip = (".",) + tuple(metadata_prefixes)
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if not d.startswith(skip))
                for f in sorted(files):
                    if not f.startswith(skip):
                        out.append(os.path.join(root, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


class FileBasedDatasource(Datasource):
    """One ReadTask per file group (parity: file_based_datasource.py).

    Subclasses that can decode from raw bytes implement ``_decode_bytes``;
    their read tasks then batch-read each group through the native IO pool
    (``ray_tpu.native.io_pool``, C++ pthread pread — GIL-free), decoding in
    Python while the remaining files stream in the background."""

    #: prefixes of non-data entries to skip during expansion (parquet-family
    #: sources set ("_",) for their sidecar metadata)
    _metadata_prefixes: tuple = ()

    def __init__(self, paths, **read_kwargs):
        # remember the user-supplied directory roots: hive partition-value
        # parsing must only consider path segments BELOW a root, never
        # unrelated ancestors (/tmp/run=3/... must not become a column)
        raw = [paths] if isinstance(paths, str) else list(paths)
        self.root_dirs = [os.path.abspath(p) for p in raw if isinstance(p, str) and os.path.isdir(p)]
        self.paths = _expand_paths(paths, self._metadata_prefixes)
        self.read_kwargs = read_kwargs

    def _relative_to_root(self, path: str) -> Optional[str]:
        ap = os.path.abspath(path)
        for root in self.root_dirs:
            if ap.startswith(root + os.sep):
                return ap[len(root) + 1:]
        return None

    def _read_file(self, path: str) -> Block:
        # default: read bytes then decode (subclasses override either hook)
        with open(path, "rb") as f:
            return self._decode_bytes(path, f.read())

    def _decode_bytes(self, path: str, data: bytes) -> Block:
        raise NotImplementedError

    def _supports_bytes(self) -> bool:
        return type(self)._decode_bytes is not FileBasedDatasource._decode_bytes

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        files = self.paths
        parallelism = max(1, min(parallelism, len(files) or 1))
        groups: List[List[str]] = [[] for _ in range(parallelism)]
        for i, f in enumerate(files):
            groups[i % parallelism].append(f)
        tasks = []
        for group in groups:
            if not group:
                continue

            def make(group=group):
                pool = None
                if len(group) > 1 and self._supports_bytes():
                    from ray_tpu.native.io_pool import default_pool, file_size

                    pool = default_pool()
                if pool is not None:
                    # all reads submitted up front; each file decodes as its
                    # read lands, overlapping IO with decode — memory stays
                    # ~one group of in-flight buffers, yielded one at a time
                    ranges = [(p, 0, file_size(p)) for p in group]
                    for path, data in zip(group, pool.iter_reads(ranges)):
                        yield self._decode_bytes(path, data)
                else:
                    for path in group:
                        yield self._read_file(path)

            size = sum(os.path.getsize(f) for f in group if os.path.exists(f))
            meta = BlockMetadata(num_rows=-1, size_bytes=size, input_files=group)
            tasks.append(ReadTask(make, meta))
        return tasks


class CSVDatasource(FileBasedDatasource):
    def _decode_bytes(self, path: str, data: bytes) -> Block:
        import csv
        import io

        reader = csv.DictReader(io.StringIO(data.decode(), newline=""), **self.read_kwargs)
        rows = [dict(r) for r in reader]
        block = block_from_rows(rows)
        return {k: _maybe_numeric(v) for k, v in block.items()}

    def write(self, blocks: List[Block], path: str, **kwargs) -> None:
        import csv

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(blocks):
            acc = BlockAccessor(block)
            with open(os.path.join(path, f"part-{i:05d}.csv"), "w", newline="") as f:
                keys = list(block.keys())
                w = csv.DictWriter(f, fieldnames=keys)
                w.writeheader()
                for row in acc.iter_rows():
                    w.writerow(row)


class JSONDatasource(FileBasedDatasource):
    """JSONL files, one object per line (parity: json_datasource.py)."""

    def _decode_bytes(self, path: str, data: bytes) -> Block:
        rows = []
        for line in data.decode().splitlines():
            line = line.strip()
            if line:
                rows.append(_json.loads(line))
        return block_from_rows(rows)

    def write(self, blocks: List[Block], path: str, **kwargs) -> None:
        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(blocks):
            with open(os.path.join(path, f"part-{i:05d}.jsonl"), "w") as f:
                for row in BlockAccessor(block).iter_rows():
                    f.write(_json.dumps(_jsonable(row)) + "\n")


class NumpyDatasource(FileBasedDatasource):
    def _decode_bytes(self, path: str, data: bytes) -> Block:
        import io

        arr = np.load(io.BytesIO(data), allow_pickle=False)
        return {"data": arr}

    def write(self, blocks: List[Block], path: str, *, column: str = "data", **kwargs) -> None:
        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(blocks):
            np.save(os.path.join(path, f"part-{i:05d}.npy"), block[column])


class ParquetDatasource(FileBasedDatasource):
    _metadata_prefixes = ("_",)  # _delta_log, _partition_spec.json, _SUCCESS

    """Parquet via pyarrow with column + predicate pushdown.

    Parity: ``python/ray/data/datasource/parquet_datasource.py`` — ``columns``
    prunes at the reader (only those column chunks are decoded) and
    ``filters`` (DNF-style ``[(col, op, value), ...]`` AND-list) prunes whole
    row groups via the file's min/max statistics BEFORE any IO on them, then
    applies the exact predicate to the surviving rows.

    ``read_stats`` (class-level, lock-guarded, **per-process**) records
    row-groups total vs actually read so pushdown is assertable on a direct
    read; reads executed in worker processes account in THAT process."""

    #: per-process pushdown accounting: {"row_groups_total", "row_groups_read", "files"}
    read_stats = {"row_groups_total": 0, "row_groups_read": 0, "files": 0}
    _stats_lock = threading.Lock()

    def __init__(self, paths, columns=None, filters=None, **read_kwargs):
        super().__init__(paths, **read_kwargs)
        self.columns = list(columns) if columns is not None else None
        self.filters = list(filters) if filters is not None else None

    @classmethod
    def reset_read_stats(cls) -> None:
        cls.read_stats = {"row_groups_total": 0, "row_groups_read": 0, "files": 0}

    @staticmethod
    def _group_may_match(meta_rg, col_index: Dict[str, int], filt) -> bool:
        """Can this row group contain rows matching (col, op, value)?
        Conservative: missing statistics => True."""
        col, op, value = filt
        idx = col_index.get(col)
        if idx is None:
            return True
        stats = meta_rg.column(idx).statistics
        if stats is None or not stats.has_min_max:
            return True
        lo, hi = stats.min, stats.max
        try:
            if op in ("=", "=="):
                return lo <= value <= hi
            if op == "<":
                return lo < value
            if op == "<=":
                return lo <= value
            if op == ">":
                return hi > value
            if op == ">=":
                return hi >= value
            if op == "in":
                return any(lo <= v <= hi for v in value)
            if op in ("!=", "not in"):
                return True  # min/max can't disprove inequality
        except TypeError:
            return True
        return True

    def _read_file(self, path: str) -> Block:
        import pyarrow.parquet as pq

        f = pq.ParquetFile(path, **self.read_kwargs)
        meta = f.metadata
        cls = type(self)
        with cls._stats_lock:
            cls.read_stats["files"] += 1
            cls.read_stats["row_groups_total"] += meta.num_row_groups
        if self.filters:
            col_index = {meta.schema.column(i).name: i for i in range(meta.num_columns)}
            keep = [
                g for g in range(meta.num_row_groups)
                if all(
                    self._group_may_match(meta.row_group(g), col_index, filt)
                    for filt in self.filters
                )
            ]
            with cls._stats_lock:
                cls.read_stats["row_groups_read"] += len(keep)
            want = self.columns or list(f.schema_arrow.names)
            if not keep:
                table = f.schema_arrow.empty_table().select(want)
            else:
                # the exact predicate needs its columns present: read the
                # union, filter, then project down to the requested set
                filter_cols = [filt[0] for filt in self.filters]
                read_cols = list(dict.fromkeys(want + filter_cols))
                table = f.read_row_groups(keep, columns=read_cols)
                if table.num_rows:
                    table = table.filter(pq.filters_to_expression(self.filters))
                table = table.select(want)
        else:
            with cls._stats_lock:
                cls.read_stats["row_groups_read"] += meta.num_row_groups
            table = f.read(columns=self.columns)
        # hive layout: key=value segments BELOW the dataset root come back
        # as columns (ancestor directories never do)
        hive = _hive_partition_values(self._relative_to_root(path))
        for key, value in hive.items():
            if key not in table.column_names and (
                self.columns is None or key in self.columns
            ):
                import pyarrow as pa

                table = table.append_column(key, pa.array([value] * table.num_rows))
        return BlockAccessor.for_block(table).to_block()

    def write(
        self, blocks: List[Block], path: str,
        partition_cols=None, partition_by=None, **kwargs,
    ) -> None:
        """Write blocks as parquet.  ``partition_cols=[col, ...]`` produces a
        hive layout (``col=value/`` directories, partition columns dropped
        from the files — restored at read time); ``partition_by={"column":
        c, "mode": "hash"|"range", "num_partitions": N}`` buckets rows by a
        deterministic hash or by global range boundaries (reference:
        partitioned writes in parquet_datasource + partitioning.py)."""
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        if partition_cols:
            self._write_hive(blocks, path, list(partition_cols))
            return
        if partition_by:
            self._write_bucketed(blocks, path, dict(partition_by))
            return
        for i, block in enumerate(blocks):
            table = BlockAccessor(block).to_arrow()
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    @staticmethod
    def _hive_quote(value) -> str:
        from urllib.parse import quote

        return quote(str(value), safe="")

    def _write_hive(self, blocks, path: str, cols: List[str]) -> None:
        import pyarrow.parquet as pq

        part_seq: Dict[str, int] = {}
        for block in blocks:
            table = BlockAccessor(block).to_arrow()
            key_arrays = [np.asarray(table[c]) for c in cols]
            for c, arr in zip(cols, key_arrays):
                # NaN != NaN would silently drop those rows (no combo mask
                # matches); nulls in partition columns are a modeling error
                if arr.dtype.kind == "f" and np.isnan(arr).any():
                    raise ValueError(
                        f"partition column {c!r} contains NaN/null values; "
                        "partition keys must be non-null"
                    )
            keys = list(zip(*[a.tolist() for a in key_arrays])) if len(table) else []
            data = table.drop_columns(cols)
            for combo in sorted(set(keys), key=str):
                mask = np.ones(len(table), dtype=bool)
                for arr, v in zip(key_arrays, combo):
                    mask &= arr == v
                subdir = os.path.join(
                    path, *[f"{c}={self._hive_quote(v)}" for c, v in zip(cols, combo)]
                )
                os.makedirs(subdir, exist_ok=True)
                seq = part_seq.get(subdir, 0)
                part_seq[subdir] = seq + 1
                pq.write_table(
                    data.filter(mask), os.path.join(subdir, f"part-{seq:05d}.parquet")
                )

    def _write_bucketed(self, blocks, path: str, spec: dict) -> None:
        import json as _json

        import pyarrow.parquet as pq

        column = spec["column"]
        n = int(spec.get("num_partitions", 8))
        mode = spec.get("mode", "hash")
        tables = [BlockAccessor(b).to_arrow() for b in blocks]
        if mode == "range":
            chunks = [np.asarray(t[column]) for t in tables if len(t)]
            if not chunks:
                # empty dataset: spec-only layout, nothing to bucket
                with open(os.path.join(path, "_partition_spec.json"), "w") as f:
                    import json as _j

                    _j.dump({"column": column, "mode": mode,
                             "num_partitions": n, "bounds": []}, f)
                return
            all_vals = np.concatenate(chunks)
            if all_vals.dtype.kind not in "iuf":
                raise ValueError(
                    f"range partitioning needs a numeric column; {column!r} "
                    f"has dtype {all_vals.dtype}"
                )
            bounds = [
                float(np.quantile(all_vals, q))
                for q in np.linspace(0, 1, n + 1)[1:-1]
            ]
        elif mode == "hash":
            bounds = None
        else:
            raise ValueError(f"partition_by mode must be 'hash' or 'range', got {mode!r}")
        with open(os.path.join(path, "_partition_spec.json"), "w") as f:
            _json.dump({"column": column, "mode": mode, "num_partitions": n, "bounds": bounds}, f)
        part_seq: Dict[int, int] = {}
        for table in tables:
            vals = np.asarray(table[column])
            if mode == "range":
                idx = np.searchsorted(np.asarray(bounds), vals, side="right")
            else:
                idx = np.asarray([_stable_hash(v) % n for v in vals.tolist()])
            for part in sorted(set(idx.tolist())):
                subdir = os.path.join(path, f"{mode}={part:04d}")
                os.makedirs(subdir, exist_ok=True)
                seq = part_seq.get(part, 0)
                part_seq[part] = seq + 1
                pq.write_table(
                    table.filter(idx == part),
                    os.path.join(subdir, f"part-{seq:05d}.parquet"),
                )


def _stable_hash(value) -> int:
    """Deterministic across processes (Python's str hash is salted)."""
    import hashlib

    return int.from_bytes(
        hashlib.blake2b(repr(value).encode(), digest_size=8).digest(), "little"
    )


def coerce_partition_value(raw) -> Any:
    """THE string->value promotion for partition values ('try int, then
    float, else str') — shared by hive parquet and Delta partitionValues
    so the policies can't drift."""
    if not isinstance(raw, str):
        return raw
    for cast in (int, float):
        try:
            return cast(raw)
        except (TypeError, ValueError):
            continue
    return raw


def _hive_partition_values(rel_path: Optional[str]) -> Dict[str, Any]:
    """key=value segments of a root-RELATIVE path -> column values."""
    from urllib.parse import unquote

    out: Dict[str, Any] = {}
    if not rel_path:
        return out
    for segment in rel_path.split(os.sep)[:-1]:
        key, sep, raw = segment.partition("=")
        if not sep or not key or key in ("hash", "range"):
            continue
        out[key] = coerce_partition_value(unquote(raw))
    return out


def _maybe_numeric(arr: np.ndarray) -> np.ndarray:
    """CSV reads everything as str; promote to numbers when they parse."""
    if arr.dtype != object and not np.issubdtype(arr.dtype, np.str_):
        return arr
    vals = list(arr)
    try:
        return np.asarray([int(v) for v in vals], dtype=np.int64)
    except (TypeError, ValueError):
        pass
    try:
        return np.asarray([float(v) for v in vals], dtype=np.float64)
    except (TypeError, ValueError):
        return arr


def _jsonable(row: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in row.items():
        if isinstance(v, np.ndarray):
            out[k] = v.tolist()
        elif isinstance(v, np.generic):
            out[k] = v.item()
        else:
            out[k] = v
    return out


class TextDatasource(FileBasedDatasource):
    """One row per line (parity: text_datasource.py)."""

    def _decode_bytes(self, path: str, data: bytes) -> Block:
        text = data.decode(self.read_kwargs.get("encoding", "utf-8"))
        # split on \n ONLY (file-iteration semantics): splitlines() would
        # also break rows at \x0c, \x85,  ... inside a line
        text = text.replace("\r\n", "\n").replace("\r", "\n")  # universal newlines
        lines = text.split("\n")
        if lines and lines[-1] == "":
            lines.pop()  # trailing newline is a terminator, not an empty row
        if self.read_kwargs.get("drop_empty_lines", True):
            lines = [ln for ln in lines if ln]
        return {"text": np.asarray(lines, dtype=object)}


class BinaryDatasource(FileBasedDatasource):
    """Whole files as bytes rows (parity: binary_datasource.py)."""

    def _decode_bytes(self, path: str, data: bytes) -> Block:
        block = {"bytes": np.asarray([bytes(data)], dtype=object)}
        if self.read_kwargs.get("include_paths", False):
            block["path"] = np.asarray([path], dtype=object)
        return block


class ImageDatasource(FileBasedDatasource):
    """Images decoded to HWC uint8 arrays via PIL (parity:
    image_datasource.py). ``size=(h, w)`` resizes; ``mode`` converts."""

    def _decode_bytes(self, path: str, data: bytes) -> Block:
        import io
        from PIL import Image

        img = Image.open(io.BytesIO(data))
        mode = self.read_kwargs.get("mode")
        if mode:
            img = img.convert(mode)
        size = self.read_kwargs.get("size")
        if size:
            img = img.resize((size[1], size[0]))  # PIL takes (w, h)
        arr = np.asarray(img)
        block = {"image": arr[None, ...]}
        if self.read_kwargs.get("include_paths", False):
            block["path"] = np.asarray([path], dtype=object)
        return block


class WebDatasetDatasource(FileBasedDatasource):
    """WebDataset-style tar shards: files sharing a basename form one sample,
    keyed by extension (parity: webdataset_datasource.py). Decodes .json,
    .txt/.cls, .npy, and common image extensions; other payloads stay bytes."""

    IMAGE_EXTS = {"jpg", "jpeg", "png", "bmp", "gif", "webp"}

    def _decode_bytes(self, path: str, data: bytes) -> Block:
        import io
        import tarfile

        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(fileobj=io.BytesIO(data)) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                # webdataset convention: key = member name up to the first
                # dot AFTER the last '/', so dots in directories don't split
                dirname, _, filename = member.name.rpartition("/")
                stem, _, ext = filename.partition(".")
                base = f"{dirname}/{stem}" if dirname else stem
                ext = ext.lower()
                payload = tf.extractfile(member).read()
                if base not in samples:
                    samples[base] = {"__key__": base}
                    order.append(base)
                samples[base][ext] = self._decode(ext, payload)
        rows = [samples[k] for k in order]
        return block_from_rows(rows)

    def _decode(self, ext: str, payload: bytes):
        import io

        # a multi-part extension like "seg.png" decodes by its LAST suffix
        last = ext.rsplit(".", 1)[-1]
        if last == "json":
            return _json.loads(payload)
        if last == "cls":
            text = payload.decode()
            try:
                return int(text)
            except ValueError:
                return text
        if last == "txt":
            return payload.decode()
        if last == "npy":
            return np.load(io.BytesIO(payload), allow_pickle=False)
        if last in self.IMAGE_EXTS:
            try:
                from PIL import Image

                return np.asarray(Image.open(io.BytesIO(payload)))
            except Exception:
                return payload
        return payload


class ImageWriteDatasource(FileBasedDatasource):
    """Write an image column as one file per row (parity:
    image_datasource write path / ``Dataset.write_images``).  Arrays are
    encoded via PIL; raw ``bytes`` values are written as-is."""

    def write(self, blocks: List[Block], path: str, *, column: str = "image",
              file_format: str = "png", **kwargs) -> None:
        os.makedirs(path, exist_ok=True)
        i = 0
        for block in blocks:
            for row in BlockAccessor(block).iter_rows():
                value = row[column]
                out = os.path.join(path, f"{i:08d}.{file_format}")
                if isinstance(value, (bytes, bytearray)):
                    with open(out, "wb") as f:
                        f.write(value)
                else:
                    from PIL import Image

                    arr = np.asarray(value)
                    if arr.dtype != np.uint8:
                        arr = np.clip(arr, 0, 255).astype(np.uint8)
                    Image.fromarray(arr).save(out, format=file_format.upper())
                i += 1


class WebDatasetWriteDatasource(FileBasedDatasource):
    """Write webdataset tar shards — the mirror of WebDatasetDatasource's
    reader: one tar per block, one member per (row, column), keyed
    ``{__key__}.{column-extension}`` so a read round-trips.  Column values
    encode by type: str -> .txt, int -> .cls, dict/list -> .json,
    ndarray -> .npy, bytes -> kept under the column name as extension."""

    def write(self, blocks: List[Block], path: str, **kwargs) -> None:
        import io
        import tarfile

        os.makedirs(path, exist_ok=True)
        counter = 0
        for shard_idx, block in enumerate(blocks):
            out = os.path.join(path, f"shard-{shard_idx:06d}.tar")
            with tarfile.open(out, "w") as tf:
                for row in BlockAccessor(block).iter_rows():
                    key = str(row.get("__key__", f"{counter:08d}"))
                    counter += 1
                    for col, value in row.items():
                        if col == "__key__":
                            continue
                        name, payload = self._encode(key, col, value)
                        info = tarfile.TarInfo(name=name)
                        info.size = len(payload)
                        tf.addfile(info, io.BytesIO(payload))

    @staticmethod
    def _encode(key: str, col: str, value) -> tuple:
        """Encode by the column's extension suffix (webdataset columns are
        extension-named: jpg/txt/cls/json/npy); non-extension column names
        get a type-derived suffix appended so the payload stays decodable."""
        import io

        if isinstance(value, (bytes, bytearray)):
            return f"{key}.{col}", bytes(value)
        last = col.rsplit(".", 1)[-1].lower()
        if last == "json":
            return f"{key}.{col}", _json.dumps(_jsonable({"v": value})["v"]).encode()
        if last == "cls":
            return f"{key}.{col}", str(int(value)).encode()
        if last == "txt":
            return f"{key}.{col}", str(value).encode()
        if last == "npy":
            buf = io.BytesIO()
            np.save(buf, np.asarray(value), allow_pickle=False)
            return f"{key}.{col}", buf.getvalue()
        if last in WebDatasetDatasource.IMAGE_EXTS:
            from PIL import Image

            arr = np.asarray(value)
            if arr.dtype != np.uint8:
                arr = np.clip(arr, 0, 255).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="PNG" if last == "png" else "JPEG")
            return f"{key}.{col}", buf.getvalue()
        # type-derived suffix for plain column names
        if isinstance(value, str):
            return f"{key}.{col}.txt", value.encode()
        if isinstance(value, (int, np.integer)):
            return f"{key}.{col}.cls", str(int(value)).encode()
        if isinstance(value, np.ndarray):
            buf = io.BytesIO()
            np.save(buf, value, allow_pickle=False)
            return f"{key}.{col}.npy", buf.getvalue()
        return f"{key}.{col}.json", _json.dumps(_jsonable({"v": value})["v"]).encode()


class SQLDatasource(Datasource):
    """DB-API 2.0 query reads (parity: sql_datasource.py — ``read_sql``
    takes a query + zero-arg connection factory; rows become columnar
    blocks). Parallelism is 1 unless the caller provides shard queries —
    DB-API cursors can't be split safely in general."""

    def __init__(self, sql: str, connection_factory, shard_queries=None):
        self.sql = sql
        self.connection_factory = connection_factory
        self.shard_queries = list(shard_queries) if shard_queries else [sql]

    def get_name(self) -> str:
        return "SQL"

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        factory = self.connection_factory
        tasks = []
        for query in self.shard_queries:
            def make(query=query):
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(query)
                    cols = [d[0] for d in cur.description]
                    rows = cur.fetchall()
                finally:
                    conn.close()
                if not rows:
                    return []
                block = {
                    c: np.asarray([r[i] for r in rows])
                    for i, c in enumerate(cols)
                }
                return [block]

            tasks.append(ReadTask(make, BlockMetadata(num_rows=-1, size_bytes=-1)))
        return tasks

    def write(self, blocks: List[Block], table: str, **kwargs) -> None:
        """Insert blocks into ``table`` (backs ``Dataset.write_sql``).

        ``paramstyle`` kwarg picks the DB-API placeholder: "qmark" (sqlite)
        or "format" (postgres/mysql drivers). The table name must be a
        plain identifier — it is interpolated into the statement.
        """
        if not table.replace("_", "").isalnum():
            raise ValueError(f"table name must be a plain identifier, got {table!r}")
        placeholder = {"qmark": "?", "format": "%s"}[kwargs.get("paramstyle", "qmark")]
        conn = self.connection_factory()
        try:
            cur = conn.cursor()
            for block in blocks:
                acc = BlockAccessor.for_block(block)
                data = acc.to_dict()
                cols = list(data.keys())
                placeholders = ", ".join(placeholder for _ in cols)
                stmt = f"INSERT INTO {table} ({', '.join(cols)}) VALUES ({placeholders})"
                n = acc.num_rows()
                cur.executemany(
                    stmt, [tuple(data[c][i] for c in cols) for i in range(n)]
                )
            conn.commit()
        finally:
            conn.close()


_CRC32C_TABLE: Optional[List[int]] = None


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli, poly 0x1EDC6F41 reflected) — the checksum the
    TFRecord container mandates; table-driven pure Python."""
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
            table.append(crc)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    table = _CRC32C_TABLE
    for byte in data:
        crc = table[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


class TFRecordDatasource(FileBasedDatasource):
    """TFRecord files (parity: ``tfrecords_datasource.py``).

    The container framing (8-byte little-endian length + masked-crc32c of
    the length + payload + masked-crc32c of the payload) is parsed in pure
    Python; payloads decode as ``tf.train.Example`` feature dicts when
    ``tf_schema`` decoding is on (default, requires tensorflow), else raw
    bytes rows."""

    def __init__(self, paths, decode_examples: bool = True, **read_kwargs):
        super().__init__(paths, **read_kwargs)
        self.decode_examples = decode_examples

    def _decode_bytes(self, path: str, data: bytes) -> Block:
        import struct as _struct

        records = []
        off = 0
        n = len(data)
        while off + 12 <= n:
            (length,) = _struct.unpack_from("<Q", data, off)
            off += 12  # length + its crc
            payload = data[off : off + length]
            off += length + 4  # payload + its crc
            records.append(payload)
        if not self.decode_examples:
            return {"bytes": np.asarray(records, dtype=object)}
        try:
            from tensorflow.core.example import example_pb2
        except ImportError as exc:  # pragma: no cover
            raise ImportError(
                "decoding tf.train.Example requires tensorflow; pass "
                "decode_examples=False for raw bytes rows"
            ) from exc
        rows = []
        for payload in records:
            ex = example_pb2.Example.FromString(payload)
            row = {}
            for name, feat in ex.features.feature.items():
                kind = feat.WhichOneof("kind")
                if kind == "bytes_list":
                    vals = list(feat.bytes_list.value)
                elif kind == "float_list":
                    vals = list(feat.float_list.value)
                elif kind == "int64_list":
                    vals = list(feat.int64_list.value)
                else:
                    vals = []
                row[name] = vals[0] if len(vals) == 1 else vals
            rows.append(row)
        return block_from_rows(rows)

    def write(self, blocks: List[Block], path: str, **kwargs) -> None:
        import struct as _struct

        def _masked_crc(b: bytes) -> int:
            # real crc32c (Castagnoli) + TFRecord masking: standard TF
            # readers VERIFY these, so anything else writes unreadable files
            crc = _crc32c(b)
            return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF

        try:
            from tensorflow.core.example import example_pb2, feature_pb2
        except ImportError as exc:  # pragma: no cover
            raise ImportError("write_tfrecords requires tensorflow") from exc

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(blocks):
            with open(os.path.join(path, f"part-{i:05d}.tfrecords"), "wb") as f:
                for row in BlockAccessor(block).iter_rows():
                    feats = {}
                    for k, v in row.items():
                        if isinstance(v, (bytes, str)):
                            raw = v.encode() if isinstance(v, str) else v
                            feats[k] = feature_pb2.Feature(
                                bytes_list=feature_pb2.BytesList(value=[raw])
                            )
                        elif isinstance(v, (bool, np.bool_, int, np.integer)):
                            feats[k] = feature_pb2.Feature(
                                int64_list=feature_pb2.Int64List(value=[int(v)])
                            )
                        elif isinstance(v, (float, np.floating)):
                            feats[k] = feature_pb2.Feature(
                                float_list=feature_pb2.FloatList(value=[float(v)])
                            )
                        elif isinstance(v, (list, np.ndarray)):
                            arr = np.asarray(v)
                            if np.issubdtype(arr.dtype, np.integer):
                                feats[k] = feature_pb2.Feature(
                                    int64_list=feature_pb2.Int64List(value=arr.astype(np.int64).tolist())
                                )
                            elif np.issubdtype(arr.dtype, np.floating):
                                feats[k] = feature_pb2.Feature(
                                    float_list=feature_pb2.FloatList(value=arr.astype(np.float32).tolist())
                                )
                            else:  # strings / bytes lists
                                feats[k] = feature_pb2.Feature(
                                    bytes_list=feature_pb2.BytesList(
                                        value=[
                                            x.encode() if isinstance(x, str) else bytes(x)
                                            for x in arr.tolist()
                                        ]
                                    )
                                )
                        else:
                            raise ValueError(
                                f"write_tfrecords: column {k!r} has unsupported "
                                f"value type {type(v).__name__}"
                            )
                    payload = example_pb2.Example(
                        features=feature_pb2.Features(feature=feats)
                    ).SerializeToString()
                    header = _struct.pack("<Q", len(payload))
                    f.write(header)
                    f.write(_struct.pack("<I", _masked_crc(header)))
                    f.write(payload)
                    f.write(_struct.pack("<I", _masked_crc(payload)))


class MongoDatasource(Datasource):
    """MongoDB collections (parity: ``mongo_datasource.py``); requires
    pymongo (not bundled — gated with a clear error)."""

    def __init__(self, uri: str, database: str, collection: str, pipeline: Optional[list] = None):
        try:
            import pymongo  # noqa: F401
        except ImportError as exc:
            raise ImportError("read_mongo requires pymongo (pip install pymongo)") from exc
        self.uri, self.database, self.collection = uri, database, collection
        self.pipeline = pipeline or []

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        import pymongo

        uri, db, coll, pipeline = self.uri, self.database, self.collection, self.pipeline
        # shard by document ranges: count once, then $skip/$limit windows —
        # each ReadTask streams its slice in its own worker
        client = pymongo.MongoClient(uri)
        try:
            total = client[db][coll].count_documents({})
        finally:
            client.close()
        parallelism = max(1, min(parallelism, total or 1))
        bounds = [round(i * total / parallelism) for i in range(parallelism + 1)]
        tasks = []
        for i in range(parallelism):
            lo, hi = bounds[i], bounds[i + 1]
            if hi <= lo:
                continue

            def make(lo=lo, hi=hi):
                import pymongo as _pm

                cl = _pm.MongoClient(uri)
                try:
                    shard_pipeline = list(pipeline) + [{"$skip": lo}, {"$limit": hi - lo}]
                    rows = [
                        {k: v for k, v in doc.items() if k != "_id"}
                        for doc in cl[db][coll].aggregate(shard_pipeline)
                    ]
                finally:
                    cl.close()
                yield block_from_rows(rows)

            tasks.append(
                ReadTask(make, BlockMetadata(num_rows=hi - lo, size_bytes=-1, input_files=[uri]))
            )
        return tasks


class BigQueryDatasource(Datasource):
    """BigQuery tables/queries (parity: ``bigquery_datasource.py``);
    requires google-cloud-bigquery (not bundled — gated)."""

    def __init__(self, project_id: str, query: Optional[str] = None, dataset: Optional[str] = None):
        try:
            from google.cloud import bigquery  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "read_bigquery requires google-cloud-bigquery (pip install google-cloud-bigquery)"
            ) from exc
        self.project_id, self.query, self.dataset = project_id, query, dataset

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        project, query, dataset = self.project_id, self.query, self.dataset
        if dataset is not None and query is None:
            # table reads shard by row ranges via list_rows(start_index)
            from google.cloud import bigquery

            client = bigquery.Client(project=project)
            total = client.get_table(dataset).num_rows
            parallelism = max(1, min(parallelism, int(total) or 1))
            bounds = [round(i * total / parallelism) for i in range(parallelism + 1)]
            tasks = []
            for i in range(parallelism):
                lo, hi = bounds[i], bounds[i + 1]
                if hi <= lo:
                    continue

                def make(lo=lo, hi=hi):
                    from google.cloud import bigquery as _bq

                    cl = _bq.Client(project=project)
                    rows = cl.list_rows(dataset, start_index=lo, max_results=hi - lo)
                    yield BlockAccessor.for_block(rows.to_arrow()).to_block()

                tasks.append(
                    ReadTask(make, BlockMetadata(num_rows=hi - lo, size_bytes=-1, input_files=[project]))
                )
            return tasks

        # arbitrary queries can't be split without rewriting the SQL: one
        # task (matching the reference's query path)
        def make():
            from google.cloud import bigquery

            client = bigquery.Client(project=project)
            table = client.query(query).to_arrow()
            yield BlockAccessor.for_block(table).to_block()

        return [ReadTask(make, BlockMetadata(num_rows=-1, size_bytes=-1, input_files=[project]))]
