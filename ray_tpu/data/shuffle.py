"""All-to-all exchange: sort / hash groupby / random shuffle / repartition.

Parity: ``python/ray/data/_internal/planner/exchange/``.  Two strategies,
toggled by ``DataContext.use_push_based_shuffle`` (reference toggle:
``python/ray/data/context.py:241``):

  * **push-based (default)** — the Exoshuffle scheduler
    (``push_based_shuffle_task_scheduler.py:400``): map tasks run in rounds
    whose outputs push into a bounded set of merge tasks that pre-combine
    partition slices while later rounds still map; the final reduce combines
    one merged partial per round.  See :func:`_run_push_exchange`.
  * **pull-based fallback** — the simple two-stage exchange: every map task
    partitions its block into N slices (``num_returns=N``); each reduce task
    pulls slice j from every map task and finalizes (sort-merge, aggregate,
    or concat).
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks, _sortable


# ---------------------------------------------------------------- map stage
def _partition_for_sort(block: Block, key, descending: bool, boundaries: List[Any]) -> List[Block]:
    acc = BlockAccessor(block)
    n_parts = len(boundaries) + 1
    if acc.num_rows() == 0:
        return [{} for _ in range(n_parts)]
    first_key = key if isinstance(key, str) else key[0]
    col = _sortable(block[first_key])
    idx = np.searchsorted(np.asarray(boundaries), col, side="right")
    if descending:
        idx = (n_parts - 1) - idx
    return [acc.take(np.nonzero(idx == p)[0]) for p in range(n_parts)]


def _stable_key_hash(v: Any) -> int:
    """Deterministic 64-bit hash of one partition-key value.

    Python's ``hash()`` is salted per process (PYTHONHASHSEED), so two map
    tasks in different worker processes could send the SAME string key to
    DIFFERENT reduce partitions — a groupby/repartition correctness bug,
    not just a repro nit. blake2b over a type-tagged encoding is identical
    everywhere. Numeric values hash by VALUE like Python dict keys
    (``2 == 2.0 == True`` land in one partition)."""
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, bool):
        v = int(v)
    elif isinstance(v, float) and v.is_integer():
        v = int(v)
    if isinstance(v, str):
        tag, payload = b"s", v.encode("utf-8", "surrogatepass")
    elif isinstance(v, bytes):
        tag, payload = b"b", v
    elif isinstance(v, int):
        tag, payload = b"i", str(v).encode()
    elif isinstance(v, float):
        tag, payload = b"f", repr(v).encode()
    elif v is None:
        tag, payload = b"n", b""
    else:
        tag, payload = b"o", repr(v).encode()
    return int.from_bytes(hashlib.blake2b(tag + payload, digest_size=8).digest(), "big")


def _partition_by_hash(block: Block, key: str, n_parts: int) -> List[Block]:
    acc = BlockAccessor(block)
    if acc.num_rows() == 0:
        return [{} for _ in range(n_parts)]
    col = block[key]
    hashes = np.asarray([_stable_key_hash(v) % n_parts for v in col])
    return [acc.take(np.nonzero(hashes == p)[0]) for p in range(n_parts)]


def _partition_random(block: Block, n_parts: int, seed: Optional[int]) -> List[Block]:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return [{} for _ in range(n_parts)]
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n_parts, size=n)
    return [acc.take(np.nonzero(assign == p)[0]) for p in range(n_parts)]


def _partition_round_robin(block: Block, n_parts: int, offset: int) -> List[Block]:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return [{} for _ in range(n_parts)]
    assign = (np.arange(n) + offset) % n_parts
    return [acc.take(np.nonzero(assign == p)[0]) for p in range(n_parts)]


# ------------------------------------------------------------- reduce stage
def _reduce_concat(*parts: Block) -> Block:
    return concat_blocks(list(parts))


def _reduce_sorted(key, descending: bool, *parts: Block) -> Block:
    merged = concat_blocks(list(parts))
    if not merged:
        return merged
    return BlockAccessor(merged).sort(key, descending)


def _reduce_aggregate(key: Optional[str], aggs, *parts: Block) -> Block:
    from ray_tpu.data.block import block_from_rows

    merged = concat_blocks(list(parts))
    if not merged:
        return {}
    acc = BlockAccessor(merged)
    if key is None:
        row = {a.name: a.finalize(a.accumulate_block(a.init(), merged)) for a in aggs}
        return block_from_rows([row])
    order = acc.sort_indices(key)
    sorted_block = acc.take(order)
    col = sorted_block[key]
    # group boundaries in the sorted key column
    keys_sortable = _sortable(col)
    change = np.nonzero(keys_sortable[1:] != keys_sortable[:-1])[0] + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [len(col)]])
    sacc = BlockAccessor(sorted_block)
    rows = []
    for s, e in zip(starts, ends):
        group = sacc.slice(int(s), int(e))
        row = {key: sorted_block[key][s].item() if isinstance(sorted_block[key][s], np.generic) else sorted_block[key][s]}
        for a in aggs:
            row[a.name] = a.finalize(a.accumulate_block(a.init(), group))
        rows.append(row)
    return block_from_rows(rows)


# --------------------------------------------------------------- boundaries
def sample_sort_boundaries(blocks: List[Block], key, n_parts: int) -> List[Any]:
    """Sample input blocks to pick quantile boundaries for a range partition
    (parity: exchange/sort_task_spec.py sample_boundaries)."""
    first_key = key if isinstance(key, str) else key[0]
    samples = []
    for b in blocks:
        if b and len(b.get(first_key, ())):
            col = _sortable(b[first_key])
            k = min(len(col), 20)
            samples.append(np.random.default_rng(0).choice(col, size=k, replace=False))
    if not samples:
        return []
    allv = np.sort(np.concatenate(samples))
    qs = [allv[int(i * len(allv) / n_parts)] for i in range(1, n_parts)]
    return list(qs)


# ----------------------------------------------------- push-based scheduling
class PushShuffleSchedule:
    """The round/merge structure of one push-based shuffle run (parity:
    ``_PushBasedShuffleStage`` in
    ``push_based_shuffle_task_scheduler.py:400``)."""

    def __init__(self, num_inputs: int, n_parts: int, maps_per_round: int, num_mergers: int):
        self.num_inputs = num_inputs
        self.n_parts = n_parts
        self.maps_per_round = maps_per_round
        self.num_rounds = -(-num_inputs // maps_per_round)
        self.num_mergers = num_mergers
        # contiguous partition ranges, one per merger
        base, extra = divmod(n_parts, num_mergers)
        self.merger_ranges: List[Tuple[int, int]] = []
        start = 0
        for j in range(num_mergers):
            size = base + (1 if j < extra else 0)
            self.merger_ranges.append((start, start + size))
            start += size

    def __repr__(self):
        return (
            f"PushShuffleSchedule(inputs={self.num_inputs}, parts={self.n_parts}, "
            f"rounds={self.num_rounds}x{self.maps_per_round} maps, "
            f"mergers={self.num_mergers})"
        )


#: Schedule of the most recent push-based exchange (test/diagnostic hook).
last_push_schedule: Optional[PushShuffleSchedule] = None


def _run_push_exchange(
    input_refs: List[Any],
    map_fn: Callable[[Block], List[Block]],
    reduce_fn: Callable[..., Block],
    n_parts: int,
) -> Tuple[List[Any], List[Any]]:
    """Pipelined push-based (Exoshuffle) exchange: map -> merge -> reduce.

    Parity with the reference's large-scale shuffle
    (``push_based_shuffle_task_scheduler.py:400``; Exoshuffle,
    ``README.rst:99``): map tasks run in rounds; each round's outputs are
    immediately pushed into a BOUNDED set of merge tasks (one per contiguous
    partition range) that pre-combine partials while later map rounds are
    still running; the final reduce per partition combines one merged
    partial per round instead of one slice per map task.  This caps the
    live-object count at O(rounds x parts + round_size x parts) instead of
    O(maps x parts) and overlaps map/merge — the property that makes 100
    GB-class sorts feasible (BASELINE.md target #3).

    Submission here is async end-to-end: because the fabric resolves
    dependencies through object refs, round r's merges run while round r+1's
    maps execute — the pipelining falls out of ref-based dataflow with no
    bespoke scheduler loop."""
    global last_push_schedule
    import ray_tpu

    M = len(input_refs)
    ctx = _data_context()
    try:
        cpus = max(1, int(ray_tpu.cluster_resources().get("CPU", 2)))
    except Exception:  # noqa: BLE001
        cpus = 2
    maps_per_round = max(2, min(ctx.max_tasks_in_flight, cpus * 2))
    num_mergers = max(1, min(n_parts, cpus))
    sched = PushShuffleSchedule(M, n_parts, maps_per_round, num_mergers)
    last_push_schedule = sched

    @ray_tpu.remote
    def push_map(block: Block):
        parts = map_fn(block)
        return parts[0] if len(parts) == 1 else tuple(parts)

    @ray_tpu.remote
    def push_merge(n_slices: int, *parts: Block):
        """Pre-combine this merger's partition slices across one round's
        maps. parts layout: [map0_slice0..map0_sliceK, map1_slice0..]."""
        merged = []
        for s in range(n_slices):
            merged.append(concat_blocks([parts[m * n_slices + s] for m in range(len(parts) // n_slices)]))
        return merged[0] if n_slices == 1 else tuple(merged)

    @ray_tpu.remote
    def push_reduce(*parts: Block):
        out = reduce_fn(*parts)
        meta = BlockAccessor(out).get_metadata()
        return out, meta

    # merge_out[r][j] -> list of per-slice refs for merger j in round r
    merge_out: List[List[List[Any]]] = []
    for r in range(sched.num_rounds):
        round_inputs = input_refs[r * maps_per_round : (r + 1) * maps_per_round]
        round_maps = []
        for ref in round_inputs:
            refs = push_map.options(num_returns=n_parts).remote(ref)
            if n_parts == 1:
                refs = [refs]
            round_maps.append(refs)
        round_merges: List[List[Any]] = []
        for j, (lo, hi) in enumerate(sched.merger_ranges):
            n_slices = hi - lo
            if n_slices == 0:
                round_merges.append([])
                continue
            args = [m[p] for m in round_maps for p in range(lo, hi)]
            out = push_merge.options(num_returns=n_slices).remote(n_slices, *args)
            if n_slices == 1:
                out = [out]
            round_merges.append(list(out))
        merge_out.append(round_merges)

    out_refs, meta_refs = [], []
    for j, (lo, hi) in enumerate(sched.merger_ranges):
        for o in range(hi - lo):
            parts = [merge_out[r][j][o] for r in range(sched.num_rounds)]
            block_ref, meta_ref = push_reduce.options(num_returns=2).remote(*parts)
            out_refs.append(block_ref)
            meta_refs.append(meta_ref)
    metas = ray_tpu.get(meta_refs)
    return out_refs, metas


def _data_context():
    from ray_tpu.data.context import DataContext

    return DataContext.get_current()


# ---------------------------------------------------------------- the driver
def run_exchange(
    input_refs: List[Any],
    *,
    kind: str,
    n_parts: int,
    key=None,
    descending: bool = False,
    aggs=None,
    seed: Optional[int] = None,
) -> Tuple[List[Any], List[Any]]:
    """Run the two-stage exchange; returns (output_refs, output_metadata).

    kind: "sort" | "groupby" | "shuffle" | "repartition"
    """
    n_parts = max(1, n_parts)

    if kind == "sort":
        sampled = ray_tpu.get(input_refs[: min(len(input_refs), 8)])
        boundaries = sample_sort_boundaries(sampled, key, n_parts)
        n_parts = len(boundaries) + 1
        map_fn = lambda b: _partition_for_sort(b, key, descending, boundaries)
        reduce_fn = lambda *parts: _reduce_sorted(key, descending, *parts)
    elif kind == "groupby":
        if key is None:
            n_parts = 1
            map_fn = lambda b: [b]
        else:
            map_fn = lambda b: _partition_by_hash(b, key, n_parts)
        reduce_fn = lambda *parts: _reduce_aggregate(key, aggs, *parts)
        if key is not None:
            # keep reduced partitions globally sorted by key for determinism
            pass
    elif kind == "shuffle":
        map_fn = lambda b: _partition_random(b, n_parts, seed)
        reduce_fn = _reduce_concat
    elif kind == "repartition":
        map_fn = lambda b: _partition_round_robin(b, n_parts, 0)
        reduce_fn = _reduce_concat
    else:  # pragma: no cover
        raise ValueError(kind)

    if _data_context().use_push_based_shuffle and len(input_refs) > 1:
        return _run_push_exchange(input_refs, map_fn, reduce_fn, n_parts)

    @ray_tpu.remote
    def exchange_map(block: Block):
        parts = map_fn(block)
        if len(parts) == 1:
            return parts[0]
        return tuple(parts)

    @ray_tpu.remote
    def exchange_reduce(*parts: Block):
        out = reduce_fn(*parts)
        meta = BlockAccessor(out).get_metadata()
        return out, meta

    map_out: List[List[Any]] = []
    for ref in input_refs:
        refs = exchange_map.options(num_returns=n_parts).remote(ref)
        if n_parts == 1:
            refs = [refs]
        map_out.append(refs)

    out_refs, meta_refs = [], []
    for p in range(n_parts):
        block_ref, meta_ref = exchange_reduce.options(num_returns=2).remote(
            *[map_out[m][p] for m in range(len(input_refs))]
        )
        out_refs.append(block_ref)
        meta_refs.append(meta_ref)
    metas = ray_tpu.get(meta_refs)
    return out_refs, metas
