"""All-to-all exchange: sort / hash groupby / random shuffle / repartition.

Parity: ``python/ray/data/_internal/planner/exchange/`` — a two-stage
map/reduce exchange.  The map stage partitions every input block into N
partition slices (returned as N separate objects via ``num_returns=N``);
the reduce stage concatenates slice j from every map task and applies the
per-partition finalization (sort-merge, aggregate, or plain concat).

This is the push-based-shuffle topology of the Exoshuffle paper
(``push_based_shuffle_task_scheduler.py:400``) collapsed onto the in-process
fabric: map outputs are pushed directly into reducer inputs (object refs),
with no centralized shuffle service.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks, _sortable


# ---------------------------------------------------------------- map stage
def _partition_for_sort(block: Block, key, descending: bool, boundaries: List[Any]) -> List[Block]:
    acc = BlockAccessor(block)
    n_parts = len(boundaries) + 1
    if acc.num_rows() == 0:
        return [{} for _ in range(n_parts)]
    first_key = key if isinstance(key, str) else key[0]
    col = _sortable(block[first_key])
    idx = np.searchsorted(np.asarray(boundaries), col, side="right")
    if descending:
        idx = (n_parts - 1) - idx
    return [acc.take(np.nonzero(idx == p)[0]) for p in range(n_parts)]


def _partition_by_hash(block: Block, key: str, n_parts: int) -> List[Block]:
    acc = BlockAccessor(block)
    if acc.num_rows() == 0:
        return [{} for _ in range(n_parts)]
    col = block[key]
    hashes = np.asarray([hash(v.item() if isinstance(v, np.generic) else v) % n_parts for v in col])
    return [acc.take(np.nonzero(hashes == p)[0]) for p in range(n_parts)]


def _partition_random(block: Block, n_parts: int, seed: Optional[int]) -> List[Block]:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return [{} for _ in range(n_parts)]
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n_parts, size=n)
    return [acc.take(np.nonzero(assign == p)[0]) for p in range(n_parts)]


def _partition_round_robin(block: Block, n_parts: int, offset: int) -> List[Block]:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return [{} for _ in range(n_parts)]
    assign = (np.arange(n) + offset) % n_parts
    return [acc.take(np.nonzero(assign == p)[0]) for p in range(n_parts)]


# ------------------------------------------------------------- reduce stage
def _reduce_concat(*parts: Block) -> Block:
    return concat_blocks(list(parts))


def _reduce_sorted(key, descending: bool, *parts: Block) -> Block:
    merged = concat_blocks(list(parts))
    if not merged:
        return merged
    return BlockAccessor(merged).sort(key, descending)


def _reduce_aggregate(key: Optional[str], aggs, *parts: Block) -> Block:
    from ray_tpu.data.block import block_from_rows

    merged = concat_blocks(list(parts))
    if not merged:
        return {}
    acc = BlockAccessor(merged)
    if key is None:
        row = {a.name: a.finalize(a.accumulate_block(a.init(), merged)) for a in aggs}
        return block_from_rows([row])
    order = acc.sort_indices(key)
    sorted_block = acc.take(order)
    col = sorted_block[key]
    # group boundaries in the sorted key column
    keys_sortable = _sortable(col)
    change = np.nonzero(keys_sortable[1:] != keys_sortable[:-1])[0] + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [len(col)]])
    sacc = BlockAccessor(sorted_block)
    rows = []
    for s, e in zip(starts, ends):
        group = sacc.slice(int(s), int(e))
        row = {key: sorted_block[key][s].item() if isinstance(sorted_block[key][s], np.generic) else sorted_block[key][s]}
        for a in aggs:
            row[a.name] = a.finalize(a.accumulate_block(a.init(), group))
        rows.append(row)
    return block_from_rows(rows)


# --------------------------------------------------------------- boundaries
def sample_sort_boundaries(blocks: List[Block], key, n_parts: int) -> List[Any]:
    """Sample input blocks to pick quantile boundaries for a range partition
    (parity: exchange/sort_task_spec.py sample_boundaries)."""
    first_key = key if isinstance(key, str) else key[0]
    samples = []
    for b in blocks:
        if b and len(b.get(first_key, ())):
            col = _sortable(b[first_key])
            k = min(len(col), 20)
            samples.append(np.random.default_rng(0).choice(col, size=k, replace=False))
    if not samples:
        return []
    allv = np.sort(np.concatenate(samples))
    qs = [allv[int(i * len(allv) / n_parts)] for i in range(1, n_parts)]
    return list(qs)


# ---------------------------------------------------------------- the driver
def run_exchange(
    input_refs: List[Any],
    *,
    kind: str,
    n_parts: int,
    key=None,
    descending: bool = False,
    aggs=None,
    seed: Optional[int] = None,
) -> Tuple[List[Any], List[Any]]:
    """Run the two-stage exchange; returns (output_refs, output_metadata).

    kind: "sort" | "groupby" | "shuffle" | "repartition"
    """
    n_parts = max(1, n_parts)

    if kind == "sort":
        sampled = ray_tpu.get(input_refs[: min(len(input_refs), 8)])
        boundaries = sample_sort_boundaries(sampled, key, n_parts)
        n_parts = len(boundaries) + 1
        map_fn = lambda b: _partition_for_sort(b, key, descending, boundaries)
        reduce_fn = lambda *parts: _reduce_sorted(key, descending, *parts)
    elif kind == "groupby":
        if key is None:
            n_parts = 1
            map_fn = lambda b: [b]
        else:
            map_fn = lambda b: _partition_by_hash(b, key, n_parts)
        reduce_fn = lambda *parts: _reduce_aggregate(key, aggs, *parts)
        if key is not None:
            # keep reduced partitions globally sorted by key for determinism
            pass
    elif kind == "shuffle":
        map_fn = lambda b: _partition_random(b, n_parts, seed)
        reduce_fn = _reduce_concat
    elif kind == "repartition":
        map_fn = lambda b: _partition_round_robin(b, n_parts, 0)
        reduce_fn = _reduce_concat
    else:  # pragma: no cover
        raise ValueError(kind)

    @ray_tpu.remote
    def exchange_map(block: Block):
        parts = map_fn(block)
        if len(parts) == 1:
            return parts[0]
        return tuple(parts)

    @ray_tpu.remote
    def exchange_reduce(*parts: Block):
        out = reduce_fn(*parts)
        meta = BlockAccessor(out).get_metadata()
        return out, meta

    map_out: List[List[Any]] = []
    for ref in input_refs:
        refs = exchange_map.options(num_returns=n_parts).remote(ref)
        if n_parts == 1:
            refs = [refs]
        map_out.append(refs)

    out_refs, meta_refs = [], []
    for p in range(n_parts):
        block_ref, meta_ref = exchange_reduce.options(num_returns=2).remote(
            *[map_out[m][p] for m in range(len(input_refs))]
        )
        out_refs.append(block_ref)
        meta_refs.append(meta_ref)
    metas = ray_tpu.get(meta_refs)
    return out_refs, metas
