"""Dataset preprocessors: fit statistics once, transform anywhere.

Parity: ``python/ray/data/preprocessors/`` (Preprocessor base in
``preprocessor.py`` — fit/transform/fit_transform over Datasets plus
``transform_batch`` for serving-time reuse; scaler.py, encoder.py,
imputer.py, normalizer.py, concatenator.py, chain.py, batch_mapper.py,
discretizer.py, tokenizer.py, hasher.py, vectorizer.py).

TPU design: fit streams ``iter_batches`` once and reduces numpy statistics
on the host (fit is IO-bound, not a device job); transform is a pure
function of (stats, batch) applied through ``map_batches``, so it fuses
into the streaming executor and the SAME callable serves online inference
(``transform_batch``) — train/serve skew is structurally impossible.
"""

from __future__ import annotations

import collections
import functools
import hashlib
from typing import Any, Callable, Dict, List, Optional

import numpy as np

Batch = Dict[str, np.ndarray]


class PreprocessorNotFittedError(RuntimeError):
    pass


class Preprocessor:
    """Base: subclasses implement ``_fit(dataset)`` (populate ``self.stats_``)
    and ``_transform_numpy(batch)``."""

    # subclasses that need no fitting (BatchMapper, Concatenator, ...) flip this
    _is_fittable = True

    def __init__(self):
        self.stats_: Dict[str, Any] = {}
        self._fitted = not self._is_fittable

    def fit(self, dataset) -> "Preprocessor":
        if self._is_fittable:
            self._fit(dataset)
            self._fitted = True
        return self

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def transform(self, dataset):
        self._check_fitted()
        return dataset.map_batches(self._transform_numpy, batch_format="numpy")

    def transform_batch(self, batch: Batch) -> Batch:
        """Apply to one in-memory batch (online/serving path)."""
        self._check_fitted()
        return self._transform_numpy({k: np.asarray(v) for k, v in batch.items()})

    def _check_fitted(self) -> None:
        if not self._fitted:
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit() on a dataset before transform"
            )

    # -- subclass hooks -------------------------------------------------
    def _fit(self, dataset) -> None:
        raise NotImplementedError

    def _transform_numpy(self, batch: Batch) -> Batch:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(fitted={self._fitted})"


def _column_stream(dataset, columns: List[str]):
    for batch in dataset.iter_batches(batch_format="numpy"):
        yield {c: np.asarray(batch[c]) for c in columns}


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (parity: scaler.py:StandardScaler)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns

    def _fit(self, dataset) -> None:
        n = 0
        s = {c: 0.0 for c in self.columns}
        sq = {c: 0.0 for c in self.columns}
        for batch in _column_stream(dataset, self.columns):
            n += len(next(iter(batch.values())))
            for c, v in batch.items():
                s[c] += float(v.sum())
                sq[c] += float((v.astype(np.float64) ** 2).sum())
        for c in self.columns:
            mean = s[c] / max(1, n)
            var = max(0.0, sq[c] / max(1, n) - mean**2)
            self.stats_[f"mean({c})"] = mean
            self.stats_[f"std({c})"] = float(np.sqrt(var))

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            std = self.stats_[f"std({c})"] or 1.0
            out[c] = (np.asarray(batch[c], np.float64) - self.stats_[f"mean({c})"]) / std
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (parity: scaler.py:MinMaxScaler)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns

    def _fit(self, dataset) -> None:
        lo = {c: np.inf for c in self.columns}
        hi = {c: -np.inf for c in self.columns}
        for batch in _column_stream(dataset, self.columns):
            for c, v in batch.items():
                lo[c] = min(lo[c], float(v.min()))
                hi[c] = max(hi[c], float(v.max()))
        for c in self.columns:
            self.stats_[f"min({c})"] = lo[c]
            self.stats_[f"max({c})"] = hi[c]

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats_[f"min({c})"], self.stats_[f"max({c})"]
            span = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return out


class OrdinalEncoder(Preprocessor):
    """Category -> dense int index, ordered by sorted unique value
    (parity: encoder.py:OrdinalEncoder). Unseen values -> -1."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns

    def _fit(self, dataset) -> None:
        uniques = {c: set() for c in self.columns}
        for batch in _column_stream(dataset, self.columns):
            for c, v in batch.items():
                uniques[c].update(v.tolist())
        for c in self.columns:
            self.stats_[f"unique_values({c})"] = {
                v: i for i, v in enumerate(sorted(uniques[c]))
            }

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            table = self.stats_[f"unique_values({c})"]
            out[c] = np.array([table.get(v, -1) for v in batch[c].tolist()], np.int64)
        return out


class LabelEncoder(OrdinalEncoder):
    """OrdinalEncoder for the single label column (parity: encoder.py)."""

    def __init__(self, label_column: str):
        super().__init__([label_column])
        self.label_column = label_column


class OneHotEncoder(Preprocessor):
    """Category -> one-hot vector column (parity: encoder.py:OneHotEncoder).
    Unseen values encode to all-zeros."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns

    def _fit(self, dataset) -> None:
        enc = OrdinalEncoder(self.columns)
        enc._fit(dataset)
        self.stats_ = enc.stats_

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            table = self.stats_[f"unique_values({c})"]
            vec = np.zeros((len(batch[c]), len(table)), np.float64)
            for i, v in enumerate(batch[c].tolist()):
                j = table.get(v)
                if j is not None:
                    vec[i, j] = 1.0
            out[c] = vec
        return out


class SimpleImputer(Preprocessor):
    """Fill missing values (NaN, or None in object columns) with the
    column mean / most_frequent / a constant (parity: imputer.py)."""

    def __init__(self, columns: List[str], strategy: str = "mean", fill_value=None):
        super().__init__()
        if strategy not in ("mean", "most_frequent", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' needs fill_value")
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        if strategy == "constant":
            self._is_fittable = False
            self._fitted = True

    def _fit(self, dataset) -> None:
        if self.strategy == "mean":
            s = {c: 0.0 for c in self.columns}
            n = {c: 0 for c in self.columns}
            for batch in _column_stream(dataset, self.columns):
                for c, v in batch.items():
                    v = v.astype(np.float64)
                    mask = ~np.isnan(v)
                    s[c] += float(v[mask].sum())
                    n[c] += int(mask.sum())
            for c in self.columns:
                self.stats_[f"mean({c})"] = s[c] / max(1, n[c])
        else:  # most_frequent
            counts = {c: collections.Counter() for c in self.columns}
            for batch in _column_stream(dataset, self.columns):
                for c, v in batch.items():
                    counts[c].update(x for x in v.tolist() if x is not None and x == x)
            for c in self.columns:
                if not counts[c]:
                    raise ValueError(
                        f"SimpleImputer(strategy='most_frequent'): column {c!r} "
                        "has no non-missing values to fit on"
                    )
                self.stats_[f"most_frequent({c})"] = counts[c].most_common(1)[0][0]

    def _fill_for(self, c: str):
        if self.strategy == "constant":
            return self.fill_value
        if self.strategy == "mean":
            return self.stats_[f"mean({c})"]
        return self.stats_[f"most_frequent({c})"]

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            v = batch[c]
            fill = self._fill_for(c)
            if v.dtype.kind == "f":
                out[c] = np.where(np.isnan(v), fill, v)
            else:
                out[c] = np.array(
                    [fill if (x is None or x != x) else x for x in v.tolist()]
                )
        return out


class Normalizer(Preprocessor):
    """Row-wise unit-norm over a set of numeric columns treated as one
    vector (parity: normalizer.py; norms l1/l2/max)."""

    _is_fittable = False

    def __init__(self, columns: List[str], norm: str = "l2"):
        super().__init__()
        if norm not in ("l1", "l2", "max"):
            raise ValueError(f"unknown norm {norm!r}")
        self.columns = columns
        self.norm = norm

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        mat = np.stack([np.asarray(batch[c], np.float64) for c in self.columns], axis=1)
        if self.norm == "l1":
            denom = np.abs(mat).sum(axis=1)
        elif self.norm == "l2":
            denom = np.sqrt((mat**2).sum(axis=1))
        else:
            denom = np.abs(mat).max(axis=1)
        denom = np.where(denom == 0, 1.0, denom)
        for i, c in enumerate(self.columns):
            out[c] = mat[:, i] / denom
        return out


class Concatenator(Preprocessor):
    """Pack several numeric columns into one vector column, dropping the
    originals (parity: concatenator.py)."""

    _is_fittable = False

    def __init__(self, columns: List[str], output_column_name: str = "concat_out"):
        super().__init__()
        self.columns = columns
        self.output_column_name = output_column_name

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = {k: v for k, v in batch.items() if k not in self.columns}
        parts = []
        for c in self.columns:
            v = np.asarray(batch[c], np.float64)
            parts.append(v[:, None] if v.ndim == 1 else v.reshape(len(v), -1))
        out[self.output_column_name] = np.concatenate(parts, axis=1)
        return out


class BatchMapper(Preprocessor):
    """Arbitrary user function over batches (parity: batch_mapper.py)."""

    _is_fittable = False

    def __init__(self, fn: Callable[[Batch], Batch]):
        super().__init__()
        self.fn = fn

    def _transform_numpy(self, batch: Batch) -> Batch:
        return self.fn(batch)


class Chain(Preprocessor):
    """Sequential composition; fit propagates each stage's OUTPUT to the
    next stage's fit (parity: chain.py)."""

    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)
        self._is_fittable = any(p._is_fittable for p in self.preprocessors)
        self._fitted = not self._is_fittable

    def _fit(self, dataset) -> None:
        for i, p in enumerate(self.preprocessors):
            p.fit(dataset)
            if any(q._is_fittable for q in self.preprocessors[i + 1 :]):
                # materialize between stages: otherwise the next FIT lazily
                # re-executes the base read plus stages 0..i from scratch
                # (O(k^2) passes for k fittable stages). Skipped when no
                # later stage fits — transform-only tails don't need the
                # intermediate, and materializing it could dwarf the fit.
                dataset = p.transform(dataset).materialize()

    def transform(self, dataset):
        self._check_fitted()
        for p in self.preprocessors:
            dataset = p.transform(dataset)
        return dataset

    def transform_batch(self, batch: Batch) -> Batch:
        self._check_fitted()
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch


class KBinsDiscretizer(Preprocessor):
    """Continuous -> bin index, uniform or quantile edges (parity:
    discretizer.py Uniform/CustomKBinsDiscretizer).

    ``strategy="uniform"`` fits in O(1) memory. ``strategy="quantile"``
    computes EXACT quantiles and therefore materializes the fitted columns
    on the host during fit — prefer uniform (or subsample first) for
    columns larger than RAM."""

    def __init__(self, columns: List[str], bins: int = 5, strategy: str = "uniform"):
        super().__init__()
        if strategy not in ("uniform", "quantile"):
            raise ValueError(f"unknown strategy {strategy!r}")
        self.columns = columns
        self.bins = bins
        self.strategy = strategy

    def _fit(self, dataset) -> None:
        if self.strategy == "uniform":
            mm = MinMaxScaler(self.columns)
            mm._fit(dataset)
            for c in self.columns:
                lo, hi = mm.stats_[f"min({c})"], mm.stats_[f"max({c})"]
                self.stats_[f"edges({c})"] = np.linspace(lo, hi, self.bins + 1)[1:-1]
        else:
            vals = {c: [] for c in self.columns}
            for batch in _column_stream(dataset, self.columns):
                for c, v in batch.items():
                    vals[c].append(v.astype(np.float64))
            for c in self.columns:
                allv = np.concatenate(vals[c])
                qs = np.linspace(0, 1, self.bins + 1)[1:-1]
                self.stats_[f"edges({c})"] = np.quantile(allv, qs)

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            out[c] = np.digitize(
                np.asarray(batch[c], np.float64), self.stats_[f"edges({c})"]
            ).astype(np.int64)
        return out


def _default_tokenize(s: str) -> List[str]:
    return s.lower().split()


@functools.lru_cache(maxsize=65536)
def _hash_bucket(token: str, num_features: int) -> int:
    # md5, not hash(): stable across processes/PYTHONHASHSEED
    return int.from_bytes(hashlib.md5(token.encode()).digest()[:8], "little") % num_features


class Tokenizer(Preprocessor):
    """String column -> list-of-tokens column (parity: tokenizer.py)."""

    _is_fittable = False

    def __init__(self, columns: List[str], tokenization_fn: Optional[Callable] = None):
        super().__init__()
        self.columns = columns
        self.fn = tokenization_fn or _default_tokenize

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            rows = [self.fn(str(s)) for s in batch[c].tolist()]
            # np.array(..., dtype=object) would build a 2-D array whenever
            # every row has the same token count, making the column's ndim
            # batch-dependent; preallocate so each cell is a token LIST
            col = np.empty(len(rows), dtype=object)
            for i, r in enumerate(rows):
                col[i] = r
            out[c] = col
        return out


class FeatureHasher(Preprocessor):
    """Token lists -> fixed-width count vector by hashing (parity:
    hasher.py; stable across processes via md5, not Python hash())."""

    _is_fittable = False

    def __init__(self, columns: List[str], num_features: int = 256):
        super().__init__()
        self.columns = columns
        self.num_features = num_features

    def _bucket(self, token: str) -> int:
        return _hash_bucket(token, self.num_features)

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            vec = np.zeros((len(batch[c]), self.num_features), np.float64)
            for i, tokens in enumerate(batch[c].tolist()):
                for t in tokens if not isinstance(tokens, str) else self._split(tokens):
                    vec[i, self._bucket(str(t))] += 1.0
            out[c] = vec
        return out

    @staticmethod
    def _split(s: str) -> List[str]:
        return _default_tokenize(s)


class CountVectorizer(Preprocessor):
    """Token lists / strings -> count vector over the fitted vocabulary
    (parity: vectorizer.py; optional max_features keeps the most frequent)."""

    def __init__(self, columns: List[str], max_features: Optional[int] = None):
        super().__init__()
        self.columns = columns
        self.max_features = max_features

    @staticmethod
    def _tokens(value) -> List[str]:
        return _default_tokenize(value) if isinstance(value, str) else list(value)

    def _fit(self, dataset) -> None:
        counts = {c: collections.Counter() for c in self.columns}
        for batch in _column_stream(dataset, self.columns):
            for c, v in batch.items():
                for row in v.tolist():
                    counts[c].update(str(t) for t in self._tokens(row))
        for c in self.columns:
            common = counts[c].most_common(self.max_features)
            self.stats_[f"token_counts({c})"] = {
                t: i for i, (t, _n) in enumerate(sorted(common))
            }

    def _transform_numpy(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            vocab = self.stats_[f"token_counts({c})"]
            vec = np.zeros((len(batch[c]), len(vocab)), np.float64)
            for i, row in enumerate(batch[c].tolist()):
                for t in self._tokens(row):
                    j = vocab.get(str(t))
                    if j is not None:
                        vec[i, j] += 1.0
            out[c] = vec
        return out


__all__ = [
    "Preprocessor",
    "PreprocessorNotFittedError",
    "StandardScaler",
    "MinMaxScaler",
    "OrdinalEncoder",
    "LabelEncoder",
    "OneHotEncoder",
    "SimpleImputer",
    "Normalizer",
    "Concatenator",
    "BatchMapper",
    "Chain",
    "KBinsDiscretizer",
    "Tokenizer",
    "FeatureHasher",
    "CountVectorizer",
]
