"""Batch LLM inference over Datasets.

Parity target: the reference's ``ray.data`` batch-inference pattern (a
stateful ``map_batches`` callable holding the model; their LLM guides wrap
vLLM). Here the callable wraps the native continuous-batching engine
(``ray_tpu.serve.llm.LLMEngine``): every prompt in a batch is submitted at
once, so the engine's slot scheduler packs them into shared decode steps —
offline throughput rides the same machinery as online serving.

    ds = rt.data.from_items([{"prompt": [1, 2, 3]}, ...])
    out = ds.map_batches(
        LLMPredictor,
        fn_constructor_args=(model_factory,),
        batch_size=32,
    )

The engine is cached per (process, factory), so repeated blocks on one
worker reuse the compiled decode step.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

_engine_cache: Dict[Any, Any] = {}
_cache_lock = threading.Lock()


def clear_engine_cache() -> None:
    """Shut down and release every cached engine (daemon threads + device
    KV caches + pinned params). Call between unrelated batch-inference
    jobs in a long-lived process; worker processes exit anyway."""
    with _cache_lock:
        entries = list(_engine_cache.values())
        _engine_cache.clear()
    for _factory, engine, _tok in entries:
        try:
            engine.shutdown()
        except Exception:
            pass


class LLMPredictor:
    """``map_batches``-compatible callable: token-id prompts in, generated
    token ids (and text, when the factory supplies a tokenizer) out."""

    def __init__(
        self,
        model_factory: Callable[[], Any],
        *,
        max_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        prompt_column: str = "prompt",
        output_column: str = "generated",
        **engine_kwargs,
    ):
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.eos_id = eos_id
        self.prompt_column = prompt_column
        self.output_column = output_column
        # Cache key: factory identity AND the engine kwargs — different
        # kwargs must not silently share an engine. The cached tuple keeps a
        # STRONG reference to the factory, so a cached id() always refers to
        # that still-alive object (no post-GC id recycling).
        key = (id(model_factory), tuple(sorted((k, repr(v)) for k, v in engine_kwargs.items())))
        with _cache_lock:
            entry = _engine_cache.get(key)
            if entry is None:
                # build INSIDE the lock: a racing constructor would
                # otherwise leak a fully-built engine (daemon thread +
                # device params)
                from ray_tpu.serve.llm import LLMEngine

                made = model_factory()
                cfg, params = made[0], made[1]
                tokenizer = made[2] if len(made) > 2 else None
                entry = _engine_cache[key] = (
                    model_factory,
                    LLMEngine(cfg, params, **engine_kwargs),
                    tokenizer,
                )
        self.engine, self.tokenizer = entry[1], entry[2]

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        prompts = batch[self.prompt_column]
        futs = []
        for p in prompts:
            if isinstance(p, str):
                if self.tokenizer is None:
                    raise ValueError(
                        "string prompts need a tokenizer (model_factory returning "
                        "(cfg, params, tokenizer)); otherwise pass token-id lists"
                    )
                p = list(self.tokenizer.encode(p))
            else:
                p = [int(t) for t in p]
            futs.append(
                self.engine.submit(
                    p,
                    max_tokens=self.max_tokens,
                    temperature=self.temperature,
                    eos_id=self.eos_id,
                )
            )
        results: List[List[int]] = [f.result() for f in futs]
        out = dict(batch)
        out[self.output_column] = _object_column(results)
        if self.tokenizer is not None:
            out[self.output_column + "_text"] = _object_column(
                [self.tokenizer.decode(r) for r in results]
            )
        return out


def _object_column(values: List[Any]) -> np.ndarray:
    """One row per VALUE — np.asarray would turn equal-length lists into a
    2-D array and break row alignment."""
    arr = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        arr[i] = v
    return arr
