"""DataContext: per-process execution knobs.

Parity: ``python/ray/data/context.py`` (``DataContext.get_current()``,
``target_max_block_size``, shuffle strategy toggle :241, etc.).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ExecutionResources:
    """Resource limits for streaming execution (parity:
    ray.data.ExecutionResources)."""

    cpu: Optional[float] = None
    gpu: Optional[float] = None
    object_store_memory: Optional[float] = None


@dataclass
class ExecutionOptions:
    """Execution knobs (parity: ray.data.ExecutionOptions).

    ``preserve_order`` orders operator outputs by dispatch;
    ``resource_limits.cpu`` caps in-flight tasks across the topology and
    ``resource_limits.object_store_memory`` caps finished-but-unconsumed
    bytes (both enforced in the streaming executor's dispatch loop).
    ``resource_limits.gpu`` and ``verbose_progress`` are accepted for
    source compatibility but have no effect here (map tasks declare their
    own num_tpus; progress verbosity is a logging knob)."""

    resource_limits: ExecutionResources = field(default_factory=ExecutionResources)
    preserve_order: bool = False
    verbose_progress: bool = False


@dataclass
class DataContext:
    read_parallelism: int = 8
    max_tasks_in_flight: int = 16
    max_outqueue_bundles: int = 32
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    use_push_based_shuffle: bool = True
    enable_progress_bars: bool = False
    shuffle_seed: Optional[int] = None
    execution_options: ExecutionOptions = field(default_factory=ExecutionOptions)

    _local = threading.local()

    @property
    def preserve_order(self) -> bool:
        """Release map outputs in dispatch order instead of completion
        order (costs head-of-line blocking). Alias of
        ``execution_options.preserve_order`` — both spellings stay in sync."""
        return self.execution_options.preserve_order

    @preserve_order.setter
    def preserve_order(self, value: bool) -> None:
        self.execution_options.preserve_order = value

    @staticmethod
    def get_current() -> "DataContext":
        ctx = getattr(DataContext._local, "ctx", None)
        if ctx is None:
            ctx = DataContext()
            DataContext._local.ctx = ctx
        return ctx
