"""DataContext: per-process execution knobs.

Parity: ``python/ray/data/context.py`` (``DataContext.get_current()``,
``target_max_block_size``, shuffle strategy toggle :241, etc.).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    read_parallelism: int = 8
    max_tasks_in_flight: int = 16
    max_outqueue_bundles: int = 32
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    use_push_based_shuffle: bool = True
    enable_progress_bars: bool = False
    shuffle_seed: Optional[int] = None
    # release map outputs in dispatch order instead of completion order
    # (parity: ExecutionOptions.preserve_order; costs head-of-line blocking)
    preserve_order: bool = False

    _local = threading.local()

    @staticmethod
    def get_current() -> "DataContext":
        ctx = getattr(DataContext._local, "ctx", None)
        if ctx is None:
            ctx = DataContext()
            DataContext._local.ctx = ctx
        return ctx
