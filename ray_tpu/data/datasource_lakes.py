"""Lakehouse-format datasources: Delta Lake, Lance, Iceberg.

Parity targets: ``python/ray/data/datasource/delta_sharing_datasource.py``
/ ``lance_datasource.py`` / ``iceberg_datasource.py``.

Delta is implemented NATIVELY (no ``deltalake`` dependency): the table's
``_delta_log/NNN.json`` commits are replayed to the set of live data files
(add/remove actions, latest ``metaData`` for partition columns), which then
read through the parquet machinery; ``write_delta`` emits the same commit
protocol, so the round trip is byte-compatible with real Delta readers for
unpartitioned/hive-partitioned JSON-commit tables (parquet checkpoints are
folded in when present).  Lance and Iceberg bind to their native libraries
when installed and fail with an actionable ImportError otherwise (the
image gates optional deps — SURVEY env rules).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.datasource import Datasource, ReadTask


# ==========================================================================
# Delta Lake (native log replay)
# ==========================================================================
def _delta_live_files(table_path: str) -> List[dict]:
    """Replay _delta_log into the live ``add`` actions (path,
    partitionValues).  Checkpoint parquet files are folded in when present
    (their rows carry the same add/remove structure)."""
    log_dir = os.path.join(table_path, "_delta_log")
    if not os.path.isdir(log_dir):
        raise FileNotFoundError(f"{table_path!r} is not a Delta table (no _delta_log)")
    entries = sorted(os.listdir(log_dir))
    checkpoint_version = -1
    adds: Dict[str, dict] = {}
    # multi-part / v2 checkpoints are not replayed here: reading a SUBSET
    # of the log silently loses data, so refuse loudly instead
    unsupported = [e for e in entries if ".checkpoint." in e and not e.endswith(".checkpoint.parquet")]
    if unsupported:
        raise NotImplementedError(
            f"unsupported Delta checkpoint format in {log_dir}: {unsupported[0]!r} "
            "(multi-part/v2 checkpoints are not supported by the native reader)"
        )
    # newest checkpoint seeds the state; later JSON commits replay on top
    checkpoints = [e for e in entries if e.endswith(".checkpoint.parquet")]
    if checkpoints:
        import pyarrow.parquet as pq

        latest = checkpoints[-1]
        checkpoint_version = int(latest.split(".")[0])
        table = pq.read_table(os.path.join(log_dir, latest))
        for row in table.to_pylist():
            add = row.get("add")
            if add and add.get("path"):
                adds[add["path"]] = add
            remove = row.get("remove")
            if remove and remove.get("path"):
                adds.pop(remove["path"], None)
    for entry in entries:
        if not entry.endswith(".json"):
            continue
        try:
            version = int(entry.split(".")[0])
        except ValueError:
            continue
        if version <= checkpoint_version:
            continue
        with open(os.path.join(log_dir, entry)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                action = json.loads(line)
                add = action.get("add")
                if add and add.get("path"):
                    adds[add["path"]] = add
                remove = action.get("remove")
                if remove and remove.get("path"):
                    adds.pop(remove["path"], None)
    return list(adds.values())


class DeltaDatasource(Datasource):
    """Read a Delta table by replaying its transaction log (module
    docstring); each live file becomes a parquet read task with its
    partitionValues restored as constant columns."""

    def __init__(self, table_path: str, columns: Optional[List[str]] = None):
        self.table_path = table_path
        self.columns = list(columns) if columns else None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        import pyarrow as pa
        import pyarrow.parquet as pq

        adds = _delta_live_files(self.table_path)
        tasks: List[ReadTask] = []
        for add in adds:
            file_path = os.path.join(self.table_path, add["path"])
            partition_values = add.get("partitionValues") or {}
            columns = self.columns

            def make(file_path=file_path, partition_values=partition_values, columns=columns):
                from ray_tpu.data.datasource import coerce_partition_value

                table = pq.read_table(
                    file_path,
                    columns=[c for c in columns if c not in partition_values] if columns else None,
                )
                for key, raw in partition_values.items():
                    if columns is not None and key not in columns:
                        continue
                    value = coerce_partition_value(raw)
                    table = table.append_column(key, pa.array([value] * table.num_rows))
                yield BlockAccessor.for_block(table).to_block()

            size = os.path.getsize(file_path) if os.path.exists(file_path) else add.get("size", 0)
            tasks.append(ReadTask(make, BlockMetadata(num_rows=-1, size_bytes=size, input_files=[file_path])))
        if not tasks:
            def empty():
                yield {}

            tasks.append(ReadTask(empty, BlockMetadata(num_rows=0, size_bytes=0)))
        return tasks


def _spark_schema(blocks: List[Block]) -> dict:
    """Arrow schema of the first block -> Spark struct-schema JSON."""
    import pyarrow as pa

    fields = []
    if blocks:
        schema = BlockAccessor(blocks[0]).to_arrow().schema
        for field in schema:
            t = field.type
            if pa.types.is_int64(t):
                name = "long"
            elif pa.types.is_integer(t):
                name = "integer"
            elif pa.types.is_float64(t):
                name = "double"
            elif pa.types.is_floating(t):
                name = "float"
            elif pa.types.is_boolean(t):
                name = "boolean"
            elif pa.types.is_binary(t) or pa.types.is_large_binary(t):
                name = "binary"
            elif pa.types.is_timestamp(t):
                name = "timestamp"
            elif pa.types.is_date(t):
                name = "date"
            else:
                name = "string"
            fields.append(
                {"name": field.name, "type": name, "nullable": bool(field.nullable), "metadata": {}}
            )
    return {"type": "struct", "fields": fields}


def write_delta_blocks(blocks: List[Block], table_path: str, mode: str = "append") -> None:
    """Emit parquet part files + a Delta JSON commit (protocol/metaData on
    the first commit).  ``mode``: append | overwrite (overwrite removes the
    previously-live files in the same commit)."""
    import pyarrow.parquet as pq

    log_dir = os.path.join(table_path, "_delta_log")
    os.makedirs(log_dir, exist_ok=True)
    existing = sorted(e for e in os.listdir(log_dir) if e.endswith(".json"))
    version = int(existing[-1].split(".")[0]) + 1 if existing else 0

    actions: List[dict] = []
    now_ms = int(time.time() * 1000)
    if version == 0:
        actions.append({"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}})
        actions.append(
            {
                "metaData": {
                    "id": str(uuid.uuid4()),
                    "format": {"provider": "parquet", "options": {}},
                    # a REAL Spark-schema document (deltalake/Spark readers
                    # parse this; "{}" would fail them)
                    "schemaString": json.dumps(_spark_schema(blocks)),
                    "partitionColumns": [],
                    "configuration": {},
                    "createdTime": now_ms,
                }
            }
        )
    if mode == "overwrite" and version > 0:
        for add in _delta_live_files(table_path):
            actions.append(
                {"remove": {"path": add["path"], "deletionTimestamp": now_ms, "dataChange": True}}
            )
    for block in blocks:
        table = BlockAccessor(block).to_arrow()
        name = f"part-{version:05d}-{uuid.uuid4().hex[:12]}.parquet"
        pq.write_table(table, os.path.join(table_path, name))
        actions.append(
            {
                "add": {
                    "path": name,
                    "partitionValues": {},
                    "size": os.path.getsize(os.path.join(table_path, name)),
                    "modificationTime": now_ms,
                    "dataChange": True,
                }
            }
        )
    commit = os.path.join(log_dir, f"{version:020d}.json")
    tmp = commit + ".tmp"
    with open(tmp, "w") as f:
        for action in actions:
            f.write(json.dumps(action) + "\n")
    os.replace(tmp, commit)


class DeltaWriteDatasource(Datasource):
    """Write side used by ``Dataset.write_delta``."""

    def __init__(self, mode: str = "append"):
        self.mode = mode

    def write(self, blocks: List[Block], path: str, *, mode: Optional[str] = None, **kw) -> None:
        write_delta_blocks(blocks, path, mode=mode or self.mode)


# ==========================================================================
# Lance (native library, gated)
# ==========================================================================
def _require(module: str, feature: str):
    try:
        return __import__(module)
    except ImportError as exc:
        raise ImportError(
            f"{feature} requires the {module!r} package, which is not installed "
            f"in this environment (pip install {module})"
        ) from exc


class LanceDatasource(Datasource):
    """Read a Lance dataset fragment-parallel (parity:
    ``lance_datasource.py``)."""

    def __init__(self, uri: str, columns: Optional[List[str]] = None, filter: Optional[str] = None):
        self.uri = uri
        self.columns = columns
        self.filter = filter

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        lance = _require("lance", "read_lance")
        ds = lance.dataset(self.uri)
        tasks: List[ReadTask] = []
        for fragment in ds.get_fragments():
            columns, filt = self.columns, self.filter

            def make(fragment=fragment, columns=columns, filt=filt):
                table = fragment.to_table(columns=columns, filter=filt)
                yield BlockAccessor.for_block(table).to_block()

            tasks.append(
                ReadTask(make, BlockMetadata(num_rows=fragment.count_rows(), size_bytes=-1))
            )
        return tasks or [ReadTask(lambda: iter(({},)), BlockMetadata(num_rows=0, size_bytes=0))]


def write_lance_blocks(blocks: List[Block], uri: str, mode: str = "create") -> None:
    lance = _require("lance", "write_lance")
    import pyarrow as pa

    tables = [BlockAccessor(b).to_arrow() for b in blocks]
    combined = pa.concat_tables(tables) if tables else pa.table({})
    lance.write_dataset(combined, uri, mode=mode)


class LanceWriteDatasource(Datasource):
    def __init__(self, mode: str = "create"):
        self.mode = mode

    def write(self, blocks: List[Block], path: str, *, mode: Optional[str] = None, **kw) -> None:
        write_lance_blocks(blocks, path, mode=mode or self.mode)


# ==========================================================================
# Iceberg (pyiceberg, gated)
# ==========================================================================
class IcebergDatasource(Datasource):
    """Read an Iceberg table via pyiceberg's scan planning (parity:
    ``iceberg_datasource.py`` — one read task per plan file)."""

    def __init__(
        self, table_identifier: str, *, catalog_kwargs: Optional[dict] = None,
        row_filter: Optional[str] = None, selected_fields: Optional[List[str]] = None,
    ):
        self.table_identifier = table_identifier
        self.catalog_kwargs = dict(catalog_kwargs or {})
        self.row_filter = row_filter
        self.selected_fields = selected_fields

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        _require("pyiceberg", "read_iceberg")
        from pyiceberg.catalog import load_catalog

        catalog = load_catalog(**self.catalog_kwargs)
        table = catalog.load_table(self.table_identifier)
        scan_kwargs: Dict[str, Any] = {}
        if self.row_filter is not None:
            scan_kwargs["row_filter"] = self.row_filter
        if self.selected_fields is not None:
            scan_kwargs["selected_fields"] = tuple(self.selected_fields)
        scan = table.scan(**scan_kwargs)
        plan_files = list(scan.plan_files())
        has_deletes = any(getattr(pf, "delete_files", None) for pf in plan_files)
        if self.row_filter is not None or has_deletes:
            # residual row filters and positional/equality deletes need
            # Iceberg's own evaluation — one task through scan.to_arrow()
            # is CORRECT, per-file raw parquet reads would not be
            def make_scan(scan=scan):
                yield BlockAccessor.for_block(scan.to_arrow()).to_block()

            return [ReadTask(make_scan, BlockMetadata(num_rows=-1, size_bytes=-1))]
        tasks: List[ReadTask] = []
        selected = self.selected_fields
        for plan_file in plan_files:
            def make(plan_file=plan_file, selected=selected):
                import pyarrow.parquet as pq

                table = pq.read_table(
                    plan_file.file.file_path.replace("file://", ""),
                    columns=list(selected) if selected else None,
                )
                yield BlockAccessor.for_block(table).to_block()

            tasks.append(
                ReadTask(
                    make,
                    BlockMetadata(
                        num_rows=plan_file.file.record_count,
                        size_bytes=plan_file.file.file_size_in_bytes,
                    ),
                )
            )
        return tasks or [ReadTask(lambda: iter(({},)), BlockMetadata(num_rows=0, size_bytes=0))]


# ==========================================================================
# Hudi (hudi-rs python binding, gated)
# ==========================================================================
class HudiDatasource(Datasource):
    """Read an Apache Hudi table file-slice-parallel (parity:
    ``python/ray/data/_internal/datasource/hudi_datasource.py`` — one read
    task per file slice from the latest snapshot)."""

    def __init__(self, table_uri: str, *, options: Optional[dict] = None):
        self.table_uri = table_uri
        self.options = dict(options or {})

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        hudi = _require("hudi", "read_hudi")
        table = hudi.HudiTable(self.table_uri, self.options)
        tasks: List[ReadTask] = []
        # the closure ships only (uri, options, paths) — the live HudiTable
        # is a native pyo3 object that cannot pickle into a remote task;
        # each task reconstructs it (same split the reference makes)
        table_uri, options = self.table_uri, self.options
        for slices in table.get_file_slices_splits(max(1, parallelism)):
            base_files = [s.base_file_relative_path() for s in slices]

            def make(base_files=base_files, table_uri=table_uri, options=options):
                import hudi as _hudi
                import pyarrow as pa

                t = _hudi.HudiTable(table_uri, options)
                batches = []
                for rel in base_files:
                    batches.extend(t.read_file_slice_by_base_file_path(rel))
                yield BlockAccessor.for_block(pa.Table.from_batches(batches)).to_block()

            tasks.append(ReadTask(make, BlockMetadata(num_rows=-1, size_bytes=-1)))
        return tasks or [ReadTask(lambda: iter(({},)), BlockMetadata(num_rows=0, size_bytes=0))]


# ==========================================================================
# Delta Sharing (delta-sharing client, gated)
# ==========================================================================
class DeltaSharingDatasource(Datasource):
    """Read a shared Delta table file-parallel through a Delta Sharing
    server (parity: ``delta_sharing_datasource.py`` — list files via the
    REST client, one read task per presigned file)."""

    def __init__(self, url: str, *, limit: Optional[int] = None,
                 version: Optional[int] = None, json_predicate_hints: Optional[str] = None):
        self.url = url
        self.limit = limit
        self.version = version
        self.json_predicate_hints = json_predicate_hints

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        _require("delta_sharing", "read_delta_sharing")
        from delta_sharing.protocol import DeltaSharingProfile, Table
        from delta_sharing.rest_client import DataSharingRestClient

        profile_path, _, fragment = self.url.partition("#")
        share, schema, table_name = fragment.split(".")
        profile = DeltaSharingProfile.read_from_file(profile_path)
        client = DataSharingRestClient(profile)
        response = client.list_files_in_table(
            Table(name=table_name, share=share, schema=schema),
            jsonPredicateHints=self.json_predicate_hints,
            limitHint=self.limit,
            version=self.version,
        )
        tasks: List[ReadTask] = []
        for add_file in response.add_files:
            def make(f=add_file):
                import pyarrow.parquet as pq

                import io
                import urllib.request

                with urllib.request.urlopen(f.url) as resp:
                    table = pq.read_table(io.BytesIO(resp.read()))
                yield BlockAccessor.for_block(table).to_block()

            tasks.append(
                ReadTask(make, BlockMetadata(num_rows=-1, size_bytes=getattr(add_file, "size", -1)))
            )
        return tasks or [ReadTask(lambda: iter(({},)), BlockMetadata(num_rows=0, size_bytes=0))]


# ==========================================================================
# ClickHouse (clickhouse-connect, gated)
# ==========================================================================
class ClickHouseDatasource(Datasource):
    """Read a ClickHouse query result as arrow blocks (parity:
    ``clickhouse_datasource.py``).  With ``order_by`` the read fans out as
    parallel OFFSET/LIMIT shards; without it a single task preserves
    correctness (unordered pagination would duplicate/drop rows)."""

    def __init__(self, table: str, dsn: str, *, columns: Optional[List[str]] = None,
                 filter: Optional[str] = None, order_by: Optional[List[str]] = None,
                 client_kwargs: Optional[dict] = None):
        self.table = table
        self.dsn = dsn
        self.columns = columns
        self.filter = filter
        self.order_by = order_by
        self.client_kwargs = dict(client_kwargs or {})

    def _query(self, extra: str = "") -> str:
        cols = ", ".join(self.columns) if self.columns else "*"
        q = f"SELECT {cols} FROM {self.table}"
        if self.filter:
            q += f" WHERE {self.filter}"
        if self.order_by:
            q += " ORDER BY " + ", ".join(self.order_by)
        return q + extra

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        _require("clickhouse_connect", "read_clickhouse")
        import clickhouse_connect

        dsn, kwargs = self.dsn, self.client_kwargs

        def connect():
            return clickhouse_connect.get_client(dsn=dsn, **kwargs)

        client = connect()
        total = client.query(f"SELECT count() FROM ({self._query()})").result_rows[0][0]
        if not self.order_by or parallelism <= 1 or total <= 1:
            def make_all():
                yield BlockAccessor.for_block(connect().query_arrow(self._query())).to_block()

            return [ReadTask(make_all, BlockMetadata(num_rows=int(total), size_bytes=-1))]
        shard = -(-int(total) // max(1, parallelism))
        tasks: List[ReadTask] = []
        for offset in range(0, int(total), shard):
            def make(offset=offset, shard=shard):
                q = self._query(f" LIMIT {shard} OFFSET {offset}")
                yield BlockAccessor.for_block(connect().query_arrow(q)).to_block()

            tasks.append(
                ReadTask(make, BlockMetadata(num_rows=min(shard, int(total) - offset), size_bytes=-1))
            )
        return tasks


# ==========================================================================
# Databricks (SQL statement execution REST API, gated on credentials)
# ==========================================================================
class DatabricksUCDatasource(Datasource):
    """Read a Databricks UC table/query via the SQL Statement Execution API
    with EXTERNAL_LINKS + ARROW_STREAM disposition (parity:
    ``read_databricks_tables``, ``databricks_uc_datasource.py`` — one read
    task per presigned result chunk)."""

    def __init__(self, *, warehouse_id: str, query: str,
                 host: Optional[str] = None, token: Optional[str] = None,
                 catalog: Optional[str] = None, schema: Optional[str] = None):
        import os

        self.warehouse_id = warehouse_id
        self.query = query
        self.host = host or os.environ.get("DATABRICKS_HOST", "")
        self.token = token or os.environ.get("DATABRICKS_TOKEN", "")
        self.catalog = catalog
        self.schema = schema
        if not self.host or not self.token:
            raise ValueError(
                "read_databricks_tables needs DATABRICKS_HOST and "
                "DATABRICKS_TOKEN (env vars or host=/token= arguments)"
            )

    def _api(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            f"https://{self.host}{path}",
            data=_json.dumps(body).encode() if body is not None else None,
            headers={"Authorization": f"Bearer {self.token}",
                     "Content-Type": "application/json"},
            method=method,
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return _json.loads(resp.read())

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        import time as _time

        body = {
            "warehouse_id": self.warehouse_id,
            "statement": self.query,
            "disposition": "EXTERNAL_LINKS",
            "format": "ARROW_STREAM",
            "wait_timeout": "30s",
        }
        if self.catalog:
            body["catalog"] = self.catalog
        if self.schema:
            body["schema"] = self.schema
        result = self._api("POST", "/api/2.0/sql/statements/", body)
        statement_id = result["statement_id"]
        while result["status"]["state"] in ("PENDING", "RUNNING"):
            _time.sleep(1.0)
            result = self._api("GET", f"/api/2.0/sql/statements/{statement_id}")
        if result["status"]["state"] != "SUCCEEDED":
            raise RuntimeError(f"databricks statement failed: {result['status']}")
        chunks = result.get("manifest", {}).get("chunks", [])
        tasks: List[ReadTask] = []
        for chunk in chunks:
            idx = chunk["chunk_index"]

            def make(idx=idx, statement_id=statement_id):
                import io
                import urllib.request

                import pyarrow as pa

                links = self._api(
                    "GET", f"/api/2.0/sql/statements/{statement_id}/result/chunks/{idx}"
                )["external_links"]
                batches = []
                for link in links:
                    with urllib.request.urlopen(link["external_link"], timeout=120) as resp:
                        with pa.ipc.open_stream(io.BytesIO(resp.read())) as reader:
                            batches.extend(reader)
                yield BlockAccessor.for_block(pa.Table.from_batches(batches)).to_block()

            tasks.append(
                ReadTask(
                    make,
                    BlockMetadata(num_rows=chunk.get("row_count", -1), size_bytes=chunk.get("byte_count", -1)),
                )
            )
        return tasks or [ReadTask(lambda: iter(({},)), BlockMetadata(num_rows=0, size_bytes=0))]
