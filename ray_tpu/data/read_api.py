"""Dataset constructors (parity: python/ray/data/read_api.py)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu.data import logical as L
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource import (
    BinaryDatasource,
    BlocksDatasource,
    CSVDatasource,
    Datasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    SQLDatasource,
    TextDatasource,
    WebDatasetDatasource,
)


def _parallelism(override: int = -1) -> int:
    return override if override > 0 else DataContext.get_current().read_parallelism


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return Dataset(L.Read(RangeDatasource(n), _parallelism(parallelism)))


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(RangeDatasource(n, tensor_shape=tuple(shape)), _parallelism(parallelism)))


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(ItemsDatasource(list(items)), _parallelism(parallelism)))


def from_numpy(arrays: Union[np.ndarray, List[np.ndarray]], *, column: str = "data") -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    blocks: List[Block] = [{column: a} for a in arrays]
    return Dataset(L.Read(BlocksDatasource(blocks), len(blocks)))


def from_blocks(blocks: List[Any]) -> Dataset:
    return Dataset(L.Read(BlocksDatasource([BlockAccessor.for_block(b).to_block() for b in blocks]), len(blocks)))


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return from_blocks(dfs)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return from_blocks(tables)


def read_csv(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return Dataset(L.Read(CSVDatasource(paths, **kw), _parallelism(parallelism)))


def read_json(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return Dataset(L.Read(JSONDatasource(paths, **kw), _parallelism(parallelism)))


def read_numpy(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return Dataset(L.Read(NumpyDatasource(paths, **kw), _parallelism(parallelism)))


def read_parquet(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return Dataset(L.Read(ParquetDatasource(paths, **kw), _parallelism(parallelism)))


def read_datasource(datasource: Datasource, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(datasource, _parallelism(parallelism)))


def read_text(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(TextDatasource(paths, **kw), parallelism=parallelism)


def read_binary_files(paths, *, include_paths: bool = False, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(
        BinaryDatasource(paths, include_paths=include_paths, **kw), parallelism=parallelism
    )


def read_images(paths, *, size=None, mode=None, include_paths: bool = False, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(
        ImageDatasource(paths, size=size, mode=mode, include_paths=include_paths, **kw),
        parallelism=parallelism,
    )


def read_webdataset(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(WebDatasetDatasource(paths, **kw), parallelism=parallelism)


def read_sql(sql: str, connection_factory, *, shard_queries=None, parallelism: int = -1) -> Dataset:
    """Rows of a DB-API query as a Dataset (parity: read_api.read_sql).

    ``connection_factory`` is a zero-arg callable returning a DB-API
    connection (e.g. ``lambda: sqlite3.connect(path)``). Pass
    ``shard_queries`` (a list of non-overlapping queries) to read in
    parallel; a single query reads serially.
    """
    return read_datasource(
        SQLDatasource(sql, connection_factory, shard_queries=shard_queries),
        parallelism=parallelism,
    )
