"""Dataset constructors (parity: python/ray/data/read_api.py)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu.data import logical as L
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource import (
    BinaryDatasource,
    BlocksDatasource,
    CSVDatasource,
    Datasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    SQLDatasource,
    TextDatasource,
    WebDatasetDatasource,
)


def _parallelism(override: int = -1) -> int:
    return override if override > 0 else DataContext.get_current().read_parallelism


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return Dataset(L.Read(RangeDatasource(n), _parallelism(parallelism)))


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(RangeDatasource(n, tensor_shape=tuple(shape)), _parallelism(parallelism)))


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(ItemsDatasource(list(items)), _parallelism(parallelism)))


def from_numpy(arrays: Union[np.ndarray, List[np.ndarray]], *, column: str = "data") -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    blocks: List[Block] = [{column: a} for a in arrays]
    return Dataset(L.Read(BlocksDatasource(blocks), len(blocks)))


def from_blocks(blocks: List[Any]) -> Dataset:
    return Dataset(L.Read(BlocksDatasource([BlockAccessor.for_block(b).to_block() for b in blocks]), len(blocks)))


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return from_blocks(dfs)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return from_blocks(tables)


def _from_refs(refs: List[Any]) -> Dataset:
    """Build a MaterializedDataset from already-stored block-convertible
    refs: each is normalized to a Block by a remote task.  num_returns=2
    keeps the normalized blocks in the store — the driver fetches only the
    metadata (the *_refs APIs exist precisely so payloads never transit
    the driver)."""
    import ray_tpu

    from ray_tpu.data.dataset import MaterializedDataset

    @ray_tpu.remote
    def normalize(obj):
        block = BlockAccessor.for_block(obj).to_block()
        return block, BlockAccessor(block).get_metadata()

    task = normalize.options(num_returns=2)
    block_refs, meta_refs = [], []
    for r in refs:
        b, m = task.remote(r)
        block_refs.append(b)
        meta_refs.append(m)
    return MaterializedDataset(block_refs, ray_tpu.get(meta_refs))


def from_numpy_refs(refs, *, column: str = "data") -> Dataset:
    """Refs to ndarrays (or dicts of ndarrays) -> Dataset
    (parity: from_numpy_refs)."""
    if not isinstance(refs, list):
        refs = [refs]

    import ray_tpu

    from ray_tpu.data.dataset import MaterializedDataset

    @ray_tpu.remote
    def normalize(obj):
        block = {column: obj} if isinstance(obj, np.ndarray) else BlockAccessor.for_block(obj).to_block()
        return block, BlockAccessor(block).get_metadata()

    task = normalize.options(num_returns=2)
    block_refs, meta_refs = [], []
    for r in refs:
        b, m = task.remote(r)
        block_refs.append(b)
        meta_refs.append(m)
    return MaterializedDataset(block_refs, ray_tpu.get(meta_refs))


def from_pandas_refs(refs) -> Dataset:
    """Refs to pandas DataFrames -> Dataset (parity: from_pandas_refs)."""
    return _from_refs(refs if isinstance(refs, list) else [refs])


def from_arrow_refs(refs) -> Dataset:
    """Refs to pyarrow Tables -> Dataset (parity: from_arrow_refs)."""
    return _from_refs(refs if isinstance(refs, list) else [refs])


def from_dask(df) -> Dataset:
    raise ImportError(
        "from_dask requires the dask package, which is not installed in "
        "this environment; from_pandas(df.compute()) is the native path"
    )


def from_mars(df) -> Dataset:
    raise ImportError("from_mars requires the mars package, which is not installed")


def from_modin(df) -> Dataset:
    raise ImportError(
        "from_modin requires the modin package, which is not installed; "
        "from_pandas(df._to_pandas()) is the native path"
    )


def from_spark(df, *, parallelism: int = -1) -> Dataset:
    raise ImportError(
        "from_spark requires pyspark, which is not installed; "
        "df.write.parquet + read_parquet is the native path"
    )


def read_avro(paths, *, parallelism: int = -1, **kw) -> Dataset:
    raise ImportError(
        "read_avro requires the fastavro package, which is not installed "
        "in this environment; convert with fastavro to parquet/jsonl and "
        "use read_parquet/read_json"
    )


def read_csv(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return Dataset(L.Read(CSVDatasource(paths, **kw), _parallelism(parallelism)))


def read_json(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return Dataset(L.Read(JSONDatasource(paths, **kw), _parallelism(parallelism)))


def read_numpy(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return Dataset(L.Read(NumpyDatasource(paths, **kw), _parallelism(parallelism)))


def read_parquet(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return Dataset(L.Read(ParquetDatasource(paths, **kw), _parallelism(parallelism)))


def read_parquet_bulk(paths, *, parallelism: int = -1, **kw) -> Dataset:
    """Many small parquet files, one task per file, no directory expansion
    or footer prefetch on the driver (parity: read_parquet_bulk)."""
    if isinstance(paths, str):
        paths = [paths]
    return Dataset(
        L.Read(ParquetDatasource(list(paths), **kw), max(len(paths), _parallelism(parallelism)))
    )


def read_datasource(datasource: Datasource, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(datasource, _parallelism(parallelism)))


def read_text(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(TextDatasource(paths, **kw), parallelism=parallelism)


def read_binary_files(paths, *, include_paths: bool = False, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(
        BinaryDatasource(paths, include_paths=include_paths, **kw), parallelism=parallelism
    )


def read_images(paths, *, size=None, mode=None, include_paths: bool = False, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(
        ImageDatasource(paths, size=size, mode=mode, include_paths=include_paths, **kw),
        parallelism=parallelism,
    )


def read_webdataset(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(WebDatasetDatasource(paths, **kw), parallelism=parallelism)


def read_sql(sql: str, connection_factory, *, shard_queries=None, parallelism: int = -1) -> Dataset:
    """Rows of a DB-API query as a Dataset (parity: read_api.read_sql).

    ``connection_factory`` is a zero-arg callable returning a DB-API
    connection (e.g. ``lambda: sqlite3.connect(path)``). Pass
    ``shard_queries`` (a list of non-overlapping queries) to read in
    parallel; a single query reads serially.
    """
    return read_datasource(
        SQLDatasource(sql, connection_factory, shard_queries=shard_queries),
        parallelism=parallelism,
    )


def read_tfrecords(paths, *, decode_examples: bool = True, parallelism: int = -1, **kw) -> Dataset:
    """TFRecord files (parity: read_api read_tfrecords); payloads decode as
    tf.train.Example feature dicts (requires tensorflow) or raw bytes."""
    from ray_tpu.data.datasource import TFRecordDatasource

    return Dataset(
        L.Read(TFRecordDatasource(paths, decode_examples=decode_examples, **kw), _parallelism(parallelism))
    )


def read_delta(table_path: str, *, columns=None, parallelism: int = -1) -> Dataset:
    """Delta Lake table via native _delta_log replay (parity:
    delta-sharing/deltalake readers; no deltalake dependency needed)."""
    from ray_tpu.data.datasource_lakes import DeltaDatasource

    return read_datasource(DeltaDatasource(table_path, columns=columns), parallelism=parallelism)


def read_lance(uri: str, *, columns=None, filter=None, parallelism: int = -1) -> Dataset:
    """Lance dataset, fragment-parallel (parity: lance_datasource.py;
    requires the lance package)."""
    from ray_tpu.data.datasource_lakes import LanceDatasource

    return read_datasource(
        LanceDatasource(uri, columns=columns, filter=filter), parallelism=parallelism
    )


def read_iceberg(table_identifier: str, *, catalog_kwargs=None, row_filter=None,
                 selected_fields=None, parallelism: int = -1) -> Dataset:
    """Iceberg table via pyiceberg scan planning (parity:
    iceberg_datasource.py; requires pyiceberg)."""
    from ray_tpu.data.datasource_lakes import IcebergDatasource

    return read_datasource(
        IcebergDatasource(
            table_identifier, catalog_kwargs=catalog_kwargs,
            row_filter=row_filter, selected_fields=selected_fields,
        ),
        parallelism=parallelism,
    )


def read_hudi(table_uri: str, *, options=None, parallelism: int = -1) -> Dataset:
    """Apache Hudi table, file-slice-parallel (parity: read_hudi /
    hudi_datasource.py; requires the hudi package)."""
    from ray_tpu.data.datasource_lakes import HudiDatasource

    return read_datasource(HudiDatasource(table_uri, options=options), parallelism=parallelism)


def read_delta_sharing_tables(url: str, *, limit=None, version=None,
                              json_predicate_hints=None, parallelism: int = -1) -> Dataset:
    """Shared Delta table through a Delta Sharing server, file-parallel
    (parity: read_delta_sharing_tables; requires delta-sharing).  ``url``
    is ``<profile-file>#<share>.<schema>.<table>``."""
    from ray_tpu.data.datasource_lakes import DeltaSharingDatasource

    return read_datasource(
        DeltaSharingDatasource(
            url, limit=limit, version=version, json_predicate_hints=json_predicate_hints
        ),
        parallelism=parallelism,
    )


def read_clickhouse(table: str, dsn: str, *, columns=None, filter=None,
                    order_by=None, client_kwargs=None, parallelism: int = -1) -> Dataset:
    """ClickHouse table/query as arrow blocks (parity: read_clickhouse;
    requires clickhouse-connect).  ``order_by`` enables sharded parallel
    reads."""
    from ray_tpu.data.datasource_lakes import ClickHouseDatasource

    return read_datasource(
        ClickHouseDatasource(
            table, dsn, columns=columns, filter=filter,
            order_by=order_by, client_kwargs=client_kwargs,
        ),
        parallelism=parallelism,
    )


def read_databricks_tables(*, warehouse_id: str, table: Optional[str] = None,
                           query: Optional[str] = None, catalog=None, schema=None,
                           host=None, token=None, parallelism: int = -1) -> Dataset:
    """Databricks UC table via the SQL Statement Execution API (parity:
    read_databricks_tables; needs DATABRICKS_HOST/TOKEN)."""
    from ray_tpu.data.datasource_lakes import DatabricksUCDatasource

    if (table is None) == (query is None):
        raise ValueError("pass exactly one of table= or query=")
    return read_datasource(
        DatabricksUCDatasource(
            warehouse_id=warehouse_id,
            query=query or f"SELECT * FROM {table}",
            catalog=catalog, schema=schema, host=host, token=token,
        ),
        parallelism=parallelism,
    )


def read_mongo(uri: str, database: str, collection: str, *, pipeline=None, parallelism: int = -1) -> Dataset:
    """MongoDB collection (parity: read_mongo; requires pymongo)."""
    from ray_tpu.data.datasource import MongoDatasource

    return Dataset(
        L.Read(MongoDatasource(uri, database, collection, pipeline), _parallelism(parallelism))
    )


def read_bigquery(project_id: str, *, query=None, dataset=None, parallelism: int = -1) -> Dataset:
    """BigQuery query/table (parity: read_bigquery; requires google-cloud-bigquery)."""
    from ray_tpu.data.datasource import BigQueryDatasource

    return Dataset(
        L.Read(BigQueryDatasource(project_id, query=query, dataset=dataset), _parallelism(parallelism))
    )


def from_torch(torch_dataset, *, parallelism: int = -1) -> Dataset:
    """Materialize a torch.utils.data.Dataset (map-style) or iterable
    (parity: from_torch)."""
    import builtins

    if hasattr(torch_dataset, "__getitem__") and hasattr(torch_dataset, "__len__"):
        rows = [torch_dataset[i] for i in builtins.range(len(torch_dataset))]
    else:
        rows = list(torch_dataset)
    items = []
    for row in rows:
        if isinstance(row, tuple) and len(row) == 2:
            items.append({"item": _to_numpy(row[0]), "label": _to_numpy(row[1])})
        else:
            items.append(_to_numpy(row))
    return from_items(items, parallelism=parallelism)


def from_tf(tf_dataset, *, parallelism: int = -1) -> Dataset:
    """Materialize a tf.data.Dataset (parity: from_tf)."""
    items = []
    for elem in tf_dataset.as_numpy_iterator():
        if isinstance(elem, dict):
            items.append(elem)
        elif isinstance(elem, tuple) and len(elem) == 2:
            items.append({"item": elem[0], "label": elem[1]})
        else:
            items.append(elem)
    return from_items(items, parallelism=parallelism)


def from_huggingface(hf_dataset) -> Dataset:
    """A Hugging Face datasets.Dataset rides in as Arrow (parity:
    from_huggingface).  Materialized through ``with_format("arrow")`` — NOT
    the raw ``.data`` table — so select/filter/shuffle views (which live in
    the dataset's ``_indices``) are honored."""
    table = hf_dataset.with_format("arrow")[:]
    return from_arrow(table)


def _to_numpy(x):
    if hasattr(x, "numpy"):
        try:
            return x.numpy()
        except Exception:  # noqa: BLE001
            return x
    return x
