"""ray_tpu.data: lazy streaming distributed datasets.

TPU-native rebuild of the reference's Ray Data (``python/ray/data/``,
SURVEY §2.4): columnar-numpy blocks, a logical plan with fusion rules, a
streaming executor with backpressure over the task fabric, two-stage
push-style shuffles, and an ``iter_jax_batches`` consumption path that
stages batches straight into HBM (optionally sharded over a mesh).
"""

from ray_tpu.data.aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum, Unique
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData, MaterializedDataset
from ray_tpu.data.datasource import Datasource, ReadTask
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (
    from_arrow,
    from_arrow_refs,
    from_blocks,
    from_dask,
    from_mars,
    from_modin,
    from_numpy_refs,
    from_pandas_refs,
    from_spark,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_tf,
    from_torch,
    range,
    range_tensor,
    read_avro,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_numpy,
    read_bigquery,
    read_clickhouse,
    read_databricks_tables,
    read_delta,
    read_delta_sharing_tables,
    read_hudi,
    read_iceberg,
    read_lance,
    read_mongo,
    read_parquet,
    read_parquet_bulk,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.llm_inference import LLMPredictor, clear_engine_cache
from ray_tpu.data import preprocessors
from ray_tpu.data.compute import ActorPoolStrategy, NodeIdStr, Schema, set_progress_bars
from ray_tpu.data.context import ExecutionOptions, ExecutionResources
from ray_tpu.data.datasource import (
    BlockBasedFileDatasink,
    Datasink,
    RowBasedFileDatasink,
)
from ray_tpu.data.iterator import DataIterator as DatasetIterator
from ray_tpu.data.preprocessors import Preprocessor

# legacy alias (the reference kept DatasetContext as a deprecated name)
DatasetContext = DataContext

__all__ = [
    "AggregateFn",
    "LLMPredictor",
    "preprocessors",
    "ActorPoolStrategy",
    "NodeIdStr",
    "Schema",
    "set_progress_bars",
    "ExecutionOptions",
    "ExecutionResources",
    "Datasink",
    "BlockBasedFileDatasink",
    "RowBasedFileDatasink",
    "DatasetIterator",
    "DatasetContext",
    "Preprocessor",
    "clear_engine_cache",
    "Block",
    "BlockAccessor",
    "BlockMetadata",
    "Count",
    "DataContext",
    "DataIterator",
    "Dataset",
    "Datasource",
    "GroupedData",
    "MaterializedDataset",
    "Max",
    "Mean",
    "Min",
    "ReadTask",
    "Std",
    "Sum",
    "Unique",
    "from_arrow",
    "from_arrow_refs",
    "from_dask",
    "from_mars",
    "from_modin",
    "from_numpy_refs",
    "from_pandas_refs",
    "from_spark",
    "from_huggingface",
    "from_tf",
    "from_torch",
    "from_blocks",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_datasource",
    "read_json",
    "read_numpy",
    "read_images",
    "read_avro",
    "read_parquet",
    "read_parquet_bulk",
    "read_bigquery",
    "read_clickhouse",
    "read_databricks_tables",
    "read_delta",
    "read_delta_sharing_tables",
    "read_hudi",
    "read_iceberg",
    "read_lance",
    "read_mongo",
    "read_sql",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
]
