"""Compute strategies and schema types (parity: ray.data ActorPoolStrategy
in _internal/compute.py, Schema in dataset.py)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

# node id strings as used by scheduling strategies (parity: ray.data.NodeIdStr)
NodeIdStr = str


@dataclasses.dataclass
class ActorPoolStrategy:
    """Run map UDFs in a pool of long-lived actors instead of stateless
    tasks (parity: ray.data.ActorPoolStrategy). ``size`` (or the
    ``min_size``/``max_size`` pair — the pool here is fixed at min) picks
    the pool size."""

    size: Optional[int] = None
    min_size: Optional[int] = None
    max_size: Optional[int] = None

    def __post_init__(self):
        if self.size is not None and (self.min_size or self.max_size):
            raise ValueError("pass either size or min_size/max_size, not both")


class Schema(dict):
    """Column-name -> (dtype, cell_shape) mapping with the reference's
    ``names``/``types`` accessors (parity: ray.data.Schema). Subclasses
    dict so existing callers that treated schemas as plain dicts keep
    working."""

    @property
    def names(self) -> List[str]:
        return list(self.keys())

    @property
    def types(self) -> List[Any]:
        return [v[0] if isinstance(v, tuple) else v for v in self.values()]

    def __repr__(self):
        cols = ", ".join(f"{k}: {v}" for k, v in self.items())
        return f"Schema({cols})"


def set_progress_bars(enabled: bool) -> bool:
    """Toggle executor progress bars; returns the previous value
    (parity: ray.data.set_progress_bars)."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    prev = ctx.enable_progress_bars
    ctx.enable_progress_bars = enabled
    return prev
