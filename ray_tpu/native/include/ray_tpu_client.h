// ray_tpu C++ client: the native/cross-language frontend.
//
// Parity with the reference's C++ user API surface (cpp/include/ray/api/
// object_ref.h, ray_remote.h) reshaped for this runtime: a thin TCP client
// speaking the binary client protocol (ray_tpu/util/client/binary.py)
// against a ray_tpu thin-client server. Objects are byte strings; tasks are
// Python functions addressed by importable name ("module:function") —
// cross_language.py semantics, where the "driver" may be C++ but compute
// definitions live with the runtime.
//
// Usage:
//   ray_tpu::Client c;
//   if (!c.Connect("127.0.0.1", 10001)) { ... }
//   ray_tpu::ObjectID id = c.Put("hello");
//   std::string v = c.Get(id);
//   ray_tpu::ObjectID r = c.Call("mymod:double_it", {ray_tpu::Arg::I64(21)});
//   std::string result = c.Get(r);

#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace ray_tpu {

struct ObjectID {
  uint8_t bytes[16];
  bool valid = false;
};

struct Arg {
  enum Kind : uint8_t { kBytes = 0, kRef = 1, kStr = 2, kF64 = 3, kI64 = 4 };
  Kind kind;
  std::string data;     // BYTES / STR payload
  ObjectID ref;         // REF payload
  double f64 = 0;
  int64_t i64 = 0;

  static Arg Bytes(std::string b);
  static Arg Str(std::string s);
  static Arg Ref(const ObjectID& id);
  static Arg F64(double v);
  static Arg I64(int64_t v);
};

class Client {
 public:
  Client() = default;
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Connect and send the binary-mode magic. Returns false on failure.
  bool Connect(const std::string& host, int port);
  void Close();
  bool Connected() const { return fd_ >= 0; }

  // Liveness probe; returns false on any transport error.
  bool Ping();

  // Store a byte object; returns its id (valid=false on error).
  ObjectID Put(const std::string& bytes);

  // Fetch an object's bytes. timeout_s < 0 waits forever. On error returns
  // empty string and sets last_error().
  std::string Get(const ObjectID& id, double timeout_s = -1.0);

  // Invoke a Python function by importable name with positional args;
  // returns the result object's id immediately (fetch with Get).
  ObjectID Call(const std::string& function, const std::vector<Arg>& args);

  // Drop the server-side reference.
  bool Release(const ObjectID& id);

  const std::string& last_error() const { return last_error_; }

 private:
  bool Request(uint8_t op, const std::string& payload, std::string* out);
  int fd_ = -1;
  uint64_t next_rid_ = 1;
  std::string last_error_;
};

}  // namespace ray_tpu
