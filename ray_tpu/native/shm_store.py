"""ctypes bindings for the native shared-memory object store (libtpustore.so).

The Python side maps the same POSIX shm segment with ``mmap`` for zero-copy
reads/writes; the C++ library owns allocation, the object index, refcounts and
LRU eviction (parity: plasma client ``src/ray/object_manager/plasma/client.h``
— but in-process via a shared mutex instead of a unix-socket protocol).

Builds the library on first use if g++ is available and the .so is missing.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libtpustore.so")

_lib = None
_lib_lock = threading.Lock()


def _load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            subprocess.run(
                ["make", "-s", "-C", _DIR],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.tstore_open.restype = ctypes.c_void_p
        lib.tstore_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.tstore_close.argtypes = [ctypes.c_void_p]
        lib.tstore_unlink.argtypes = [ctypes.c_char_p]
        lib.tstore_create.restype = ctypes.c_int64
        lib.tstore_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.tstore_seal.restype = ctypes.c_int
        lib.tstore_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tstore_get.restype = ctypes.c_int64
        lib.tstore_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tstore_release.restype = ctypes.c_int
        lib.tstore_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tstore_delete.restype = ctypes.c_int
        lib.tstore_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tstore_contains.restype = ctypes.c_int
        lib.tstore_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tstore_pin_range.restype = ctypes.c_int
        lib.tstore_pin_range.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tstore_prefault.restype = ctypes.c_int
        lib.tstore_prefault.argtypes = [ctypes.c_void_p]
        lib.tstore_used.restype = ctypes.c_uint64
        lib.tstore_used.argtypes = [ctypes.c_void_p]
        lib.tstore_capacity.restype = ctypes.c_uint64
        lib.tstore_capacity.argtypes = [ctypes.c_void_p]
        lib.tstore_num_objects.restype = ctypes.c_uint64
        lib.tstore_num_objects.argtypes = [ctypes.c_void_p]
        lib.tstore_evict.restype = ctypes.c_uint64
        lib.tstore_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _lib = lib
        return lib


class ShmObjectStore:
    """A named, process-shared arena of sealed immutable objects."""

    def __init__(self, name: str, capacity: int = 1 << 30, create: bool = True):
        self._lib = _load_lib()
        self.name = name
        self._handle = self._lib.tstore_open(name.encode(), capacity, 1 if create else 0)
        if not self._handle:
            raise OSError(f"failed to open shm store {name!r}")
        # Map the same segment for zero-copy python-side access.
        fd = os.open(f"/dev/shm{name}" if name.startswith("/") else f"/dev/shm/{name}", os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            self._map = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._view = memoryview(self._map)
        self._closed = False
        # base address of the mapping, for buffer-containment checks
        self._base_addr = ctypes.addressof(ctypes.c_char.from_buffer(self._map))
        self._size = len(self._map)
        # Populate THIS mapping's page tables off the hot path: the first
        # bulk memcpy through an unpopulated VMA pays a write-fault per 4K
        # page (~1.6-2.8 GB/s measured) vs ~8 GB/s once populated.  PTEs are
        # per-mapping, so every opener — creator and workers alike — warms
        # its own.  MADV_POPULATE_WRITE allocates the tmpfs pages without
        # altering contents; unsupported kernels just skip the warmup.
        import threading

        threading.Thread(
            target=self._prefault, name="shm-prefault", daemon=True
        ).start()

    _MADV_POPULATE_WRITE = 23  # Linux 5.14+

    def _prefault(self) -> None:
        # Populating commits the segment's FULL capacity in tmpfs up front.
        # Gate on free memory (skip when the arena would eat >25% of
        # MemAvailable) so small hosts keep lazy per-object allocation;
        # RAY_TPU_SHM_PREFAULT=0/1 forces either way.
        forced = os.environ.get("RAY_TPU_SHM_PREFAULT")
        if forced == "0":
            return
        if forced != "1":
            try:
                with open("/proc/meminfo") as f:
                    for line in f:
                        if line.startswith("MemAvailable:"):
                            avail_kb = int(line.split()[1])
                            if self._size > avail_kb * 1024 // 4:
                                return
                            break
            except (OSError, ValueError):
                return
        try:
            self._map.madvise(self._MADV_POPULATE_WRITE)
        except (OSError, ValueError):
            pass

    # -- plasma-style lifecycle -------------------------------------------
    def create(self, object_id: bytes, size: int, meta_size: int = 0) -> memoryview:
        """Allocate and return a writable view; call seal() when filled."""
        off = self._lib.tstore_create(self._handle, object_id, size, meta_size)
        if off == -2:
            raise FileExistsError(f"object {object_id.hex()} already exists")
        if off < 0:
            raise MemoryError(f"shm store full (need {size} bytes)")
        return self._view[off : off + size]

    def seal(self, object_id: bytes) -> None:
        if self._lib.tstore_seal(self._handle, object_id) != 0:
            raise KeyError(f"cannot seal {object_id.hex()}")

    def put(self, object_id: bytes, data, meta_size: int = 0, pin: bool = False) -> None:
        """Store and seal. With pin=True the object holds a reference and is
        exempt from LRU eviction until unpin() — used by the spill tier,
        where the shm copy is the only copy."""
        buf = self.create(object_id, len(data), meta_size)
        buf[:] = data
        self.seal(object_id)
        if pin:
            size = ctypes.c_uint64()
            meta = ctypes.c_uint64()
            self._lib.tstore_get(self._handle, object_id, ctypes.byref(size), ctypes.byref(meta))

    def unpin(self, object_id: bytes) -> None:
        self.release(object_id)

    def get(self, object_id: bytes) -> tuple[memoryview, int] | None:
        """Returns (payload_view, meta_size) pinned against eviction, or None."""
        size = ctypes.c_uint64()
        meta = ctypes.c_uint64()
        off = self._lib.tstore_get(self._handle, object_id, ctypes.byref(size), ctypes.byref(meta))
        if off < 0:
            return None
        return self._view[off : off + size.value], meta.value

    def release(self, object_id: bytes) -> None:
        self._lib.tstore_release(self._handle, object_id)

    def delete(self, object_id: bytes) -> bool:
        return self._lib.tstore_delete(self._handle, object_id) == 0

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.tstore_contains(self._handle, object_id))

    def pin_buffer(self, addr: int, nbytes: int):
        """If [addr, addr+nbytes) lies inside one SEALED entry's payload,
        pin that entry and return ``(entry_id, offset_within_payload)``;
        else None.  Pairs with release(entry_id).  This is the zero-copy
        passthrough: a buffer already living in the arena is served by
        reference, never re-staged."""
        if not (self._base_addr <= addr and addr + nbytes <= self._base_addr + self._size):
            return None
        seg_off = addr - self._base_addr
        id_out = ctypes.create_string_buffer(28)
        pay_off = ctypes.c_uint64()
        pay_size = ctypes.c_uint64()
        rc = self._lib.tstore_pin_range(
            self._handle, seg_off, id_out, ctypes.byref(pay_off), ctypes.byref(pay_size)
        )
        if rc != 0:
            return None
        rel = seg_off - pay_off.value
        if rel + nbytes > pay_size.value:  # straddles entries: not servable
            self.release(id_out.raw)
            return None
        return id_out.raw, rel

    def evict(self, num_bytes: int) -> int:
        return self._lib.tstore_evict(self._handle, num_bytes)

    # -- stats -------------------------------------------------------------
    @property
    def used_bytes(self) -> int:
        return self._lib.tstore_used(self._handle)

    @property
    def capacity(self) -> int:
        return self._lib.tstore_capacity(self._handle)

    @property
    def num_objects(self) -> int:
        return self._lib.tstore_num_objects(self._handle)

    # -- teardown ----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._view.release()
            self._map.close()
        except BufferError:
            # Zero-copy views handed out by get() are still alive; the mapping
            # is reclaimed at process exit instead.
            pass
        else:
            self._lib.tstore_close(self._handle)

    def unlink(self) -> None:
        self._lib.tstore_unlink(self.name.encode())

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
