"""ctypes bindings for the native parallel file I/O pool (libtpuio.so).

The reference's data plane reads files in native code (Arrow C++ under
``python/ray/data``'s datasources). Here a C++ pthread pool does
pread/pwrite into caller-owned buffers; ctypes calls release the GIL, so N
files stream concurrently while Python decodes the previous batch. Used by
``ray_tpu.data`` datasources for batched reads and by checkpoint writers.

Falls back cleanly: callers should catch ``OSError`` from construction and
use plain Python IO when the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libtpuio.so")

_lib = None
_lib_lock = threading.Lock()


def _locked_build(make_dir: str, lib_path: str) -> None:
    """Build under an flock so concurrent worker processes don't race
    ``make`` — without it one process can dlopen a half-linked .so and
    cache the failure for its whole lifetime."""
    import fcntl

    with open(os.path.join(make_dir, ".build.lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            if not os.path.exists(lib_path):  # a peer may have built it
                subprocess.run(["make", "-s", "-C", make_dir], check=True, capture_output=True)
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)


def _load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            _locked_build(_DIR, _LIB_PATH)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.tio_pool_create.restype = ctypes.c_void_p
        lib.tio_pool_create.argtypes = [ctypes.c_int]
        lib.tio_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.tio_file_size.restype = ctypes.c_int64
        lib.tio_file_size.argtypes = [ctypes.c_char_p]
        lib.tio_submit_read.restype = ctypes.c_uint64
        lib.tio_submit_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p,
        ]
        lib.tio_submit_write.restype = ctypes.c_uint64
        lib.tio_submit_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.tio_wait.restype = ctypes.c_int64
        lib.tio_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _lib = lib
        return lib


def file_size(path: str) -> int:
    r = _load_lib().tio_file_size(os.fspath(path).encode())
    if r < 0:
        raise OSError(-r, os.strerror(-r), path)
    return r


class IOPool:
    """A fixed pool of native reader/writer threads.

    Buffers handed to submit_* MUST stay alive until the matching wait().
    The high-level helpers (read_files / write_file) own that lifetime.
    """

    def __init__(self, num_threads: Optional[int] = None):
        self._lib = _load_lib()
        n = num_threads or min(16, (os.cpu_count() or 4))
        self._handle = self._lib.tio_pool_create(n)
        if not self._handle:
            raise OSError("failed to create native IO pool")
        self.num_threads = n
        self._closed = False
        self._pending_bufs: dict = {}

    # -- low-level ----------------------------------------------------------
    def _check_open(self) -> None:
        # use-after-close would hand the freed native pool handle to the C
        # library — a crash, not an exception; fail in Python instead
        if self._closed:
            raise RuntimeError("IOPool is closed")

    def submit_read(self, path: str, buf, offset: int = 0, length: Optional[int] = None) -> int:
        """Read [offset, offset+length) of path into buf (writable buffer)."""
        self._check_open()
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        n = length if length is not None else len(buf)
        return self._lib.tio_submit_read(
            self._handle, os.fspath(path).encode(), offset, n, addr
        )

    def submit_write(self, path: str, data, offset: int = 0, trunc: bool = True) -> int:
        self._check_open()
        # copy into a ctypes buffer so arbitrary (possibly readonly) bytes
        # stay alive until the worker thread finishes
        buf = (ctypes.c_char * len(data)).from_buffer_copy(data)
        jid = self._lib.tio_submit_write(
            self._handle, os.fspath(path).encode(), offset, len(data),
            ctypes.addressof(buf), 1 if trunc else 0,
        )
        # keep the copy alive until waited
        self._pending_bufs[jid] = buf
        return jid

    def wait(self, job_id: int) -> int:
        self._check_open()
        r = self._lib.tio_wait(self._handle, job_id)
        self._pending_bufs.pop(job_id, None)
        if r < 0:
            raise OSError(-r, os.strerror(-r))
        return r

    # -- high-level ---------------------------------------------------------
    def _drain(self, jobs) -> None:
        """Wait out in-flight jobs whose results we no longer want. MUST run
        before their buffers are freed — a native thread may still be
        writing into them (use-after-free otherwise)."""
        for jid in jobs:
            try:
                self._lib.tio_wait(self._handle, jid)
            except Exception:
                pass

    def iter_reads(self, ranges: Sequence[tuple], *, window: Optional[int] = None):
        """Generator over [(path, offset, length), ...]: keeps up to
        ``window`` reads in flight (default: pool threads + a small
        lookahead) and yields each payload in order as it completes — IO
        for later files overlaps the caller's processing of earlier ones,
        and peak memory is bounded by the window, not the whole batch.

        Exception-safe: on any error (or early generator close) every
        outstanding job is drained before its buffer can be freed."""
        ranges = list(ranges)
        w = window or (self.num_threads + 4)
        inflight: List = []  # [(buf, job_id or None)]
        idx = 0
        try:
            while idx < len(ranges) or inflight:
                while idx < len(ranges) and len(inflight) < w:
                    path, off, ln = ranges[idx]
                    idx += 1
                    if ln == 0:
                        # ctypes can't take the address of an empty buffer;
                        # an empty file is just an empty payload
                        inflight.append((bytearray(0), None))
                        continue
                    buf = bytearray(ln)
                    inflight.append((buf, self.submit_read(path, buf, offset=off, length=ln)))
                buf, jid = inflight.pop(0)
                if jid is not None:
                    n = self.wait(jid)
                    if n != len(buf):
                        del buf[n:]  # short read at EOF / file shrank
                yield buf
        finally:
            self._drain(j for _, j in inflight if j is not None)

    def read_files(self, paths: Sequence[str]) -> List[bytearray]:
        """Read whole files concurrently; returns payloads (bytes-like) in
        input order. Buffers are returned as-is — no trailing copy."""
        ranges = [(p, 0, file_size(p)) for p in paths]
        return list(self.iter_reads(ranges))

    def read_ranges(self, ranges: Sequence[tuple]) -> List[bytearray]:
        """ranges: [(path, offset, length), ...] read concurrently."""
        return list(self.iter_reads(ranges))

    def write_file(self, path: str, data) -> int:
        return self.wait(self.submit_write(path, data))

    def write_files(self, items: Sequence[tuple]) -> List[int]:
        """items: [(path, data), ...] written concurrently. On any failure
        (a bad submit OR a failed write) every in-flight job is still
        reaped — no leaked buffers or native job slots."""
        jobs: List[int] = []
        out, done = [], 0
        try:
            for p, d in items:
                jobs.append(self.submit_write(p, d))
            for jid in jobs:
                done += 1
                out.append(self.wait(jid))
        finally:
            rest = jobs[done:]
            self._drain(rest)
            for jid in rest:
                self._pending_bufs.pop(jid, None)
        return out

    # -- teardown -----------------------------------------------------------
    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._lib.tio_pool_destroy(self._handle)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


_default_pool = None  # None = untried, False = build failed (don't retry), else IOPool
_default_lock = threading.Lock()


def default_pool() -> Optional[IOPool]:
    """Process-wide shared pool, or None when the native lib can't build.
    A failed build is cached — without the sentinel every grouped read task
    would re-fork a doomed ``make`` before falling back to Python IO."""
    global _default_pool
    if _default_pool is None:
        with _default_lock:
            if _default_pool is None:
                try:
                    _default_pool = IOPool()
                except Exception:
                    _default_pool = False
    return _default_pool or None
