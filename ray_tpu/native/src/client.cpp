// C++ client implementation: binary protocol over TCP.
// See ray_tpu/native/include/ray_tpu_client.h and
// ray_tpu/util/client/binary.py (the authoritative wire format).

#include "../include/ray_tpu_client.h"

#include <arpa/inet.h>
#include <cerrno>
#include <cstring>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

namespace ray_tpu {

namespace {

constexpr char kMagic[] = "RTCPBIN1";
constexpr uint8_t kOpPing = 1;
constexpr uint8_t kOpPut = 2;
constexpr uint8_t kOpGet = 3;
constexpr uint8_t kOpCall = 4;
constexpr uint8_t kOpRelease = 5;

bool SendAll(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool RecvAll(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void PutU16(std::string* s, uint16_t v) { s->append(reinterpret_cast<char*>(&v), 2); }
void PutU32(std::string* s, uint32_t v) { s->append(reinterpret_cast<char*>(&v), 4); }
void PutF64(std::string* s, double v) { s->append(reinterpret_cast<char*>(&v), 8); }
void PutI64(std::string* s, int64_t v) { s->append(reinterpret_cast<char*>(&v), 8); }

}  // namespace

Arg Arg::Bytes(std::string b) { Arg a; a.kind = kBytes; a.data = std::move(b); return a; }
Arg Arg::Str(std::string s) { Arg a; a.kind = kStr; a.data = std::move(s); return a; }
Arg Arg::Ref(const ObjectID& id) { Arg a; a.kind = kRef; a.ref = id; return a; }
Arg Arg::F64(double v) { Arg a; a.kind = kF64; a.f64 = v; return a; }
Arg Arg::I64(int64_t v) { Arg a; a.kind = kI64; a.i64 = v; return a; }

Client::~Client() { Close(); }

bool Client::Connect(const std::string& host, int port) {
  Close();
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_str = std::to_string(port);
  if (::getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res) != 0 || !res) {
    last_error_ = "getaddrinfo failed for " + host;
    return false;
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd < 0) {
    last_error_ = "connect failed to " + host + ":" + port_str;
    return false;
  }
  if (!SendAll(fd, kMagic, 8)) {
    ::close(fd);
    last_error_ = "handshake send failed";
    return false;
  }
  fd_ = fd;
  return true;
}

void Client::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Client::Request(uint8_t op, const std::string& payload, std::string* out) {
  if (fd_ < 0) {
    last_error_ = "not connected";
    return false;
  }
  if (payload.size() > UINT32_MAX) {
    last_error_ = "payload too large (max 4 GiB)";
    return false;
  }
  const uint64_t rid = next_rid_++;
  char head[13];
  const uint32_t len = static_cast<uint32_t>(payload.size());
  std::memcpy(head, &len, 4);
  head[4] = static_cast<char>(op);
  std::memcpy(head + 5, &rid, 8);
  if (!SendAll(fd_, head, sizeof(head)) ||
      (!payload.empty() && !SendAll(fd_, payload.data(), payload.size()))) {
    last_error_ = "send failed";
    Close();
    return false;
  }
  char rhead[13];
  if (!RecvAll(fd_, rhead, sizeof(rhead))) {
    last_error_ = "recv failed";
    Close();
    return false;
  }
  uint32_t rlen;
  std::memcpy(&rlen, rhead, 4);
  const uint8_t status = static_cast<uint8_t>(rhead[4]);
  std::string body(rlen, '\0');
  if (rlen && !RecvAll(fd_, body.data(), rlen)) {
    last_error_ = "recv body failed";
    Close();
    return false;
  }
  if (status != 0) {
    last_error_ = body.empty() ? "server error" : body;
    return false;
  }
  *out = std::move(body);
  return true;
}

bool Client::Ping() {
  std::string out;
  return Request(kOpPing, "", &out) && out == "pong";
}

ObjectID Client::Put(const std::string& bytes) {
  ObjectID id;
  std::string out;
  if (!Request(kOpPut, bytes, &out)) return id;
  if (out.size() != 16) {
    last_error_ = "malformed PUT reply";
    return id;
  }
  std::memcpy(id.bytes, out.data(), 16);
  id.valid = true;
  return id;
}

std::string Client::Get(const ObjectID& id, double timeout_s) {
  std::string payload(reinterpret_cast<const char*>(id.bytes), 16);
  PutF64(&payload, timeout_s);
  std::string out;
  if (!Request(kOpGet, payload, &out)) return "";
  return out;
}

ObjectID Client::Call(const std::string& function, const std::vector<Arg>& args) {
  ObjectID invalid;
  if (args.size() > 255) {
    last_error_ = "too many args (max 255)";
    return invalid;
  }
  if (function.size() > 65535) {
    last_error_ = "function name too long";
    return invalid;
  }
  std::string payload;
  PutU16(&payload, static_cast<uint16_t>(function.size()));
  payload += function;
  payload.push_back(static_cast<char>(args.size()));
  for (const Arg& a : args) {
    payload.push_back(static_cast<char>(a.kind));
    switch (a.kind) {
      case Arg::kBytes:
      case Arg::kStr:
        PutU32(&payload, static_cast<uint32_t>(a.data.size()));
        payload += a.data;
        break;
      case Arg::kRef:
        PutU32(&payload, 16);
        payload.append(reinterpret_cast<const char*>(a.ref.bytes), 16);
        break;
      case Arg::kF64:
        PutU32(&payload, 8);
        PutF64(&payload, a.f64);
        break;
      case Arg::kI64:
        PutU32(&payload, 8);
        PutI64(&payload, a.i64);
        break;
    }
  }
  ObjectID id;
  std::string out;
  if (!Request(kOpCall, payload, &out)) return id;
  if (out.size() != 16) {
    last_error_ = "malformed CALL reply";
    return id;
  }
  std::memcpy(id.bytes, out.data(), 16);
  id.valid = true;
  return id;
}

bool Client::Release(const ObjectID& id) {
  std::string out;
  return Request(kOpRelease, std::string(reinterpret_cast<const char*>(id.bytes), 16), &out);
}

}  // namespace ray_tpu
