// Native parallel file I/O pool (libtpuio.so).
//
// The reference's data plane does file IO in native code (Arrow C++ readers
// under python/ray/data's datasources). This is the TPU rebuild's
// equivalent: a pthread pool doing pread/pwrite into caller-provided
// buffers. Python calls through ctypes, which drops the GIL for the
// duration, so N files stream concurrently while Python decodes/uses the
// previous batch — the input pipeline's job is to keep the host side of
// the TPU fed without stealing interpreter time.
//
// C ABI (no C++ types cross the boundary):
//   tio_pool_create(threads)            -> pool*
//   tio_pool_destroy(pool)
//   tio_file_size(path)                 -> int64 size | -errno
//   tio_submit_read(pool, path, off, len, dest)  -> job id
//   tio_submit_write(pool, path, off, len, src, trunc) -> job id
//   tio_wait(pool, id)                  -> int64 bytes | -errno (reaps job)
//
// Every submitted job MUST be waited on: the pool owns no buffers, the
// caller's dest/src must stay alive until tio_wait returns.

#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <thread>
#include <unordered_map>
#include <vector>
#include <unistd.h>

namespace {

struct Job {
  uint64_t id;
  bool is_write;
  bool trunc;
  std::string path;
  uint64_t offset;
  uint64_t length;
  void* buf;
  int64_t result = 0;
  bool done = false;
};

struct Pool {
  std::mutex mu;
  std::condition_variable cv_work;   // workers wait for jobs
  std::condition_variable cv_done;   // waiters wait for completion
  std::deque<Job*> queue;
  std::unordered_map<uint64_t, Job*> jobs;
  std::vector<std::thread> threads;
  uint64_t next_id = 1;
  bool stopping = false;

  explicit Pool(int n) {
    for (int i = 0; i < n; i++) {
      threads.emplace_back([this] { Run(); });
    }
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> g(mu);
      stopping = true;
    }
    cv_work.notify_all();
    for (auto& t : threads) t.join();
    for (auto& kv : jobs) delete kv.second;   // unclaimed jobs
    for (auto* j : queue) delete j;
  }

  static int64_t DoRead(Job* j) {
    int fd = open(j->path.c_str(), O_RDONLY);
    if (fd < 0) return -errno;
    size_t total = 0;
    char* dst = static_cast<char*>(j->buf);
    while (total < j->length) {
      ssize_t n = pread(fd, dst + total, j->length - total, j->offset + total);
      if (n < 0) {
        int e = errno;
        if (e == EINTR) continue;
        close(fd);
        return -e;
      }
      if (n == 0) break;  // EOF
      total += n;
    }
    close(fd);
    return static_cast<int64_t>(total);
  }

  static int64_t DoWrite(Job* j) {
    int flags = O_WRONLY | O_CREAT | (j->trunc ? O_TRUNC : 0);
    int fd = open(j->path.c_str(), flags, 0644);
    if (fd < 0) return -errno;
    size_t total = 0;
    const char* src = static_cast<const char*>(j->buf);
    while (total < j->length) {
      ssize_t n = pwrite(fd, src + total, j->length - total, j->offset + total);
      if (n < 0) {
        int e = errno;
        if (e == EINTR) continue;
        close(fd);
        return -e;
      }
      total += n;
    }
    close(fd);
    return static_cast<int64_t>(total);
  }

  void Run() {
    for (;;) {
      Job* j;
      {
        std::unique_lock<std::mutex> g(mu);
        cv_work.wait(g, [this] { return stopping || !queue.empty(); });
        if (stopping && queue.empty()) return;
        j = queue.front();
        queue.pop_front();
      }
      int64_t r = j->is_write ? DoWrite(j) : DoRead(j);
      {
        std::lock_guard<std::mutex> g(mu);
        j->result = r;
        j->done = true;
      }
      cv_done.notify_all();
    }
  }

  uint64_t Submit(Job* j) {
    std::lock_guard<std::mutex> g(mu);
    j->id = next_id++;
    jobs[j->id] = j;
    queue.push_back(j);
    cv_work.notify_one();
    return j->id;
  }

  int64_t Wait(uint64_t id) {
    std::unique_lock<std::mutex> g(mu);
    auto it = jobs.find(id);
    if (it == jobs.end()) return -EINVAL;
    Job* j = it->second;
    cv_done.wait(g, [j] { return j->done; });
    int64_t r = j->result;
    jobs.erase(it);
    delete j;
    return r;
  }
};

}  // namespace

extern "C" {

void* tio_pool_create(int threads) {
  if (threads < 1) threads = 1;
  return new Pool(threads);
}

void tio_pool_destroy(void* pool) { delete static_cast<Pool*>(pool); }

int64_t tio_file_size(const char* path) {
  struct stat st;
  if (stat(path, &st) != 0) return -errno;
  return static_cast<int64_t>(st.st_size);
}

uint64_t tio_submit_read(void* pool, const char* path, uint64_t offset,
                         uint64_t length, void* dest) {
  Job* j = new Job{0, false, false, path, offset, length, dest};
  return static_cast<Pool*>(pool)->Submit(j);
}

uint64_t tio_submit_write(void* pool, const char* path, uint64_t offset,
                          uint64_t length, void* src, int trunc) {
  Job* j = new Job{0, true, trunc != 0, path, offset, length, src};
  return static_cast<Pool*>(pool)->Submit(j);
}

int64_t tio_wait(void* pool, uint64_t id) {
  return static_cast<Pool*>(pool)->Wait(id);
}

}  // extern "C"
