// Native shared-memory object store: the host-RAM tier of the object store.
//
// Parity with the reference's plasma store (/root/reference
// src/ray/object_manager/plasma/store.h, malloc.h, eviction_policy.h):
// an mmap'd arena shared between the host runtime and CPU worker processes,
// holding immutable sealed objects addressed by 20-byte ObjectIDs, with
// zero-copy reads (workers map the same segment and read at an offset).
//
// TPU-first deltas: this tier sits BELOW the HBM object table — hot arrays
// live in HBM as jax.Arrays; this arena only holds spilled/host-bound objects
// and cross-process payloads, so the allocator favors large blocks over
// plasma's dlmalloc generality.  Layout is process-shared: a header + fixed
// open-addressing index + boundary-tagged block arena, guarded by one robust
// process-shared pthread mutex (plasma instead serializes via a unix-socket
// server thread; a shared-memory mutex removes that round trip).
//
// Object lifecycle (plasma object_lifecycle_manager.h parity):
//   CREATED (writer filling) -> SEALED (immutable, readable) -> deleted when
//   refcount hits zero and delete requested; LRU eviction over sealed,
//   unreferenced objects when an allocation doesn't fit.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7270755354524530ULL;  // "rpuSTRE0"
constexpr uint32_t kIdSize = 20;
constexpr uint32_t kNumSlots = 1 << 16;  // open-addressing index slots
constexpr uint64_t kAlign = 64;

enum SlotState : uint32_t {
  SLOT_EMPTY = 0,
  SLOT_CREATED = 1,
  SLOT_SEALED = 2,
  SLOT_TOMBSTONE = 3,
};

struct Slot {
  uint8_t id[kIdSize];
  uint32_t state;
  uint32_t refcount;
  uint64_t offset;   // offset of payload within the segment
  uint64_t size;     // payload size
  uint64_t lru_tick; // for eviction ordering
  uint64_t meta_size; // leading metadata bytes within payload (serialization envelope)
};

struct BlockHeader {
  uint64_t size;  // payload capacity of this block (excluding header)
  uint32_t free;
  uint32_t _pad;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // total segment size
  uint64_t arena_offset;  // where the block arena starts
  uint64_t arena_size;
  uint64_t used_bytes;    // payload bytes in live (created|sealed) objects
  uint64_t lru_clock;
  uint64_t num_objects;
  pthread_mutex_t mutex;
  Slot slots[kNumSlots];
  // block arena follows
};

struct Store {
  Header* hdr;
  uint8_t* base;
  uint64_t map_size;
  char name[256];
};

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

inline BlockHeader* block_at(Store* s, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(s->base + off);
}

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) { h ^= id[i]; h *= 1099511628211ULL; }
  return h;
}

Slot* find_slot(Store* s, const uint8_t* id, bool for_insert) {
  uint64_t h = hash_id(id);
  Slot* first_tombstone = nullptr;
  for (uint32_t probe = 0; probe < kNumSlots; probe++) {
    Slot* slot = &s->hdr->slots[(h + probe) & (kNumSlots - 1)];
    if (slot->state == SLOT_EMPTY) {
      if (for_insert) return first_tombstone ? first_tombstone : slot;
      return nullptr;
    }
    if (slot->state == SLOT_TOMBSTONE) {
      if (for_insert && !first_tombstone) first_tombstone = slot;
      continue;
    }
    if (memcmp(slot->id, id, kIdSize) == 0) return slot;
  }
  return for_insert ? first_tombstone : nullptr;
}

// First-fit scan over the block chain; splits oversized blocks.
int64_t arena_alloc(Store* s, uint64_t want) {
  want = align_up(want, kAlign);
  uint64_t off = s->hdr->arena_offset;
  uint64_t end = s->hdr->arena_offset + s->hdr->arena_size;
  while (off < end) {
    BlockHeader* b = block_at(s, off);
    if (b->free) {
      // coalesce forward while free
      uint64_t next = off + sizeof(BlockHeader) + b->size;
      while (next < end) {
        BlockHeader* nb = block_at(s, next);
        if (!nb->free) break;
        b->size += sizeof(BlockHeader) + nb->size;
        next = off + sizeof(BlockHeader) + b->size;
      }
      if (b->size >= want) {
        uint64_t remainder = b->size - want;
        if (remainder > sizeof(BlockHeader) + kAlign) {
          b->size = want;
          BlockHeader* split = block_at(s, off + sizeof(BlockHeader) + want);
          split->size = remainder - sizeof(BlockHeader);
          split->free = 1;
        }
        b->free = 0;
        return static_cast<int64_t>(off + sizeof(BlockHeader));
      }
    }
    off += sizeof(BlockHeader) + b->size;
  }
  return -1;
}

void arena_free(Store* s, uint64_t payload_off) {
  BlockHeader* b = block_at(s, payload_off - sizeof(BlockHeader));
  b->free = 1;
}

void delete_slot(Store* s, Slot* slot) {
  arena_free(s, slot->offset);
  s->hdr->used_bytes -= slot->size;
  s->hdr->num_objects -= 1;
  slot->state = SLOT_TOMBSTONE;
}

// Evict least-recently-used sealed, unreferenced objects until `need` bytes
// could plausibly be allocated.  Returns bytes freed.
uint64_t evict_lru(Store* s, uint64_t need) {
  uint64_t freed = 0;
  while (freed < need) {
    Slot* victim = nullptr;
    for (uint32_t i = 0; i < kNumSlots; i++) {
      Slot* slot = &s->hdr->slots[i];
      if (slot->state == SLOT_SEALED && slot->refcount == 0) {
        if (!victim || slot->lru_tick < victim->lru_tick) victim = slot;
      }
    }
    if (!victim) break;
    freed += victim->size;
    delete_slot(s, victim);
  }
  return freed;
}

class Guard {
 public:
  explicit Guard(Store* s) : s_(s) {
    int rc = pthread_mutex_lock(&s_->hdr->mutex);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&s_->hdr->mutex);
  }
  ~Guard() { pthread_mutex_unlock(&s_->hdr->mutex); }
 private:
  Store* s_;
};

}  // namespace

extern "C" {

// Open (or create) the named segment.  Returns opaque handle or null.
//
// Exactly ONE process ever initializes a segment: creation races through
// O_EXCL, and every other opener (a losing creator, or a worker mapping the
// creator's arena) WAITS for the initializer's magic instead of checking it.
// The old "init if magic missing" fallback was a real corruption: a worker
// opening in the window between the creator's ftruncate and its magic store
// would memset the header — including the process-shared mutex the creator
// might already hold — and glibc later aborts on the trampled robust mutex
// (observed as pthread_mutex_lock assertion failures under load, where the
// creator can sit descheduled in that window for hundreds of ms).
// Unlink `name` only if it still refers to the same inode we timed out on.
// Two creators timing out on one carcass would otherwise double-unlink: the
// first retries and builds a healthy segment under the name, and the second's
// bare shm_unlink(name) would then remove the HEALTHY one, splitting the
// cluster into disjoint stores.  (A window between our fstat and the unlink
// remains — POSIX has no funlinkat for shm — but it is microseconds against
// the 5-second staleness bar that gates entry to this path.)
static void unlink_if_same_inode(const char* name, dev_t dev, ino_t ino) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return;  // already gone
  struct stat st;
  if (fstat(fd, &st) == 0 && st.st_dev == dev && st.st_ino == ino) {
    shm_unlink(name);
  }
  close(fd);
}

void* tstore_open(const char* name, uint64_t capacity, int create) {
  // The segment must hold the header (index) plus a useful arena.
  const uint64_t min_capacity = align_up(sizeof(Header), kAlign) + (1ULL << 20);
  const uint64_t want_capacity = capacity;

  // attempt 0: normal open.  attempt 1 (create=1 only): the segment existed
  // but its initializer died between shm_open and storing magic/ftruncate,
  // leaving it permanently half-built — unlink the carcass and take over as
  // the O_EXCL winner ourselves.  One retry only: a second timeout means a
  // live-but-wedged initializer, which we must not yank out from under.
  for (int attempt = 0; attempt < 2; attempt++) {
    capacity = create && want_capacity < min_capacity ? min_capacity : want_capacity;
    bool initializer = false;
    int fd = -1;
    if (create) {
      fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
      if (fd >= 0) {
        initializer = true;
      } else if (errno != EEXIST) {
        return nullptr;
      }
    }
    if (fd < 0) {
      fd = shm_open(name, O_RDWR, 0600);
      if (fd < 0) {
        if (create && errno == ENOENT) continue;  // unlinked under us — recreate
        return nullptr;
      }
    }

    // Identity of the segment we actually opened — needed for a safe
    // stale-carcass unlink later (by then the name may point elsewhere).
    struct stat self_st;
    if (fstat(fd, &self_st) != 0) { close(fd); return nullptr; }

    if (initializer) {
      if (ftruncate(fd, capacity) != 0) { close(fd); shm_unlink(name); return nullptr; }
    } else {
      // wait (bounded) for the initializer to size the segment
      struct stat st;
      bool stale = false;
      for (int spin = 0; ; spin++) {
        if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
        if (st.st_size > 0) break;
        if (spin > 5000) { stale = true; break; }  // ~5s
        usleep(1000);
      }
      if (stale) {
        close(fd);
        if (create && attempt == 0) {
          unlink_if_same_inode(name, self_st.st_dev, self_st.st_ino);
          continue;
        }
        return nullptr;
      }
      capacity = st.st_size;
    }

    void* mem = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED) return nullptr;

    Store* s = new Store();
    s->hdr = reinterpret_cast<Header*>(mem);
    s->base = reinterpret_cast<uint8_t*>(mem);
    s->map_size = capacity;
    snprintf(s->name, sizeof(s->name), "%s", name);

    if (initializer) {
      memset(s->hdr, 0, sizeof(Header));
      s->hdr->capacity = capacity;
      s->hdr->arena_offset = align_up(sizeof(Header), kAlign);
      s->hdr->arena_size = capacity - s->hdr->arena_offset;
      pthread_mutexattr_t attr;
      pthread_mutexattr_init(&attr);
      pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
      pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
      pthread_mutex_init(&s->hdr->mutex, &attr);
      BlockHeader* first = block_at(s, s->hdr->arena_offset);
      first->size = s->hdr->arena_size - sizeof(BlockHeader);
      first->free = 1;
      __sync_synchronize();
      s->hdr->magic = kMagic;
    } else {
      // never initialize a segment someone else created: wait for its magic
      bool stale = false;
      for (int spin = 0; s->hdr->magic != kMagic; spin++) {
        if (spin > 5000) { stale = true; break; }
        usleep(1000);
        __sync_synchronize();
      }
      if (stale) {
        munmap(mem, capacity);
        delete s;
        if (create && attempt == 0) {
          unlink_if_same_inode(name, self_st.st_dev, self_st.st_ino);
          continue;
        }
        return nullptr;
      }
    }
    return s;
  }
  return nullptr;
}

void tstore_close(void* h) {
  Store* s = static_cast<Store*>(h);
  munmap(s->base, s->map_size);
  delete s;
}

void tstore_unlink(const char* name) { shm_unlink(name); }

// Allocate an object; returns payload offset within the segment, or:
//  -1 out of memory (even after eviction), -2 already exists.
int64_t tstore_create(void* h, const uint8_t* id, uint64_t size, uint64_t meta_size) {
  Store* s = static_cast<Store*>(h);
  Guard g(s);
  Slot* existing = find_slot(s, id, false);
  if (existing) return -2;
  int64_t off = arena_alloc(s, size ? size : 1);
  if (off < 0) {
    evict_lru(s, size);
    off = arena_alloc(s, size ? size : 1);
    if (off < 0) return -1;
  }
  Slot* slot = find_slot(s, id, true);
  if (!slot) { arena_free(s, off); return -1; }
  memcpy(slot->id, id, kIdSize);
  slot->state = SLOT_CREATED;
  slot->refcount = 1;  // creator holds a ref until seal+release
  slot->offset = off;
  slot->size = size;
  slot->meta_size = meta_size;
  slot->lru_tick = ++s->hdr->lru_clock;
  s->hdr->used_bytes += size;
  s->hdr->num_objects += 1;
  return off;
}

int tstore_seal(void* h, const uint8_t* id) {
  Store* s = static_cast<Store*>(h);
  Guard g(s);
  Slot* slot = find_slot(s, id, false);
  if (!slot || slot->state != SLOT_CREATED) return -1;
  slot->state = SLOT_SEALED;
  slot->refcount -= 1;
  return 0;
}

// Get a sealed object: returns payload offset or -1; fills size/meta_size.
// Increments refcount (pins against eviction) — pair with tstore_release.
int64_t tstore_get(void* h, const uint8_t* id, uint64_t* size_out, uint64_t* meta_size_out) {
  Store* s = static_cast<Store*>(h);
  Guard g(s);
  Slot* slot = find_slot(s, id, false);
  if (!slot || slot->state != SLOT_SEALED) return -1;
  slot->refcount += 1;
  slot->lru_tick = ++s->hdr->lru_clock;
  if (size_out) *size_out = slot->size;
  if (meta_size_out) *meta_size_out = slot->meta_size;
  return static_cast<int64_t>(slot->offset);
}

int tstore_release(void* h, const uint8_t* id) {
  Store* s = static_cast<Store*>(h);
  Guard g(s);
  Slot* slot = find_slot(s, id, false);
  if (!slot || slot->refcount == 0) return -1;
  slot->refcount -= 1;
  return 0;
}

int tstore_delete(void* h, const uint8_t* id) {
  Store* s = static_cast<Store*>(h);
  Guard g(s);
  Slot* slot = find_slot(s, id, false);
  if (!slot) return -1;
  if (slot->refcount > 0) return -2;  // pinned
  delete_slot(s, slot);
  return 0;
}

// Resolve an arbitrary segment offset to the SEALED entry whose payload
// contains it (zero-copy passthrough: a serialized buffer that already
// lives in the arena is served by referencing its entry, no staging copy).
// Fills id_out (kIdSize bytes) + payload offset/size; pins the entry
// (refcount++, pair with tstore_release) so the caller can safely offer it.
// Returns 0, or -1 when no sealed entry covers the offset.
int tstore_pin_range(void* h, uint64_t seg_off, uint8_t* id_out,
                     uint64_t* payload_off_out, uint64_t* size_out) {
  Store* s = static_cast<Store*>(h);
  Guard g(s);
  for (uint32_t i = 0; i < kNumSlots; i++) {
    Slot* slot = &s->hdr->slots[i];
    if (slot->state != SLOT_SEALED) continue;
    if (seg_off >= slot->offset && seg_off < slot->offset + slot->size) {
      slot->refcount += 1;
      slot->lru_tick = ++s->hdr->lru_clock;
      memcpy(id_out, slot->id, kIdSize);
      if (payload_off_out) *payload_off_out = slot->offset;
      if (size_out) *size_out = slot->size;
      return 0;
    }
  }
  return -1;
}

// Pre-fault THIS mapping's pages so a first bulk memcpy runs at reused-page
// rates (~8 vs ~1.6 GB/s measured).  Page-table population is per-VMA:
// every process (and every separate mapping of the segment, e.g. the
// Python-side mmap) must populate its own — callers with their own mapping
// should madvise it directly rather than rely on this one.
int tstore_prefault(void* h) {
#ifdef MADV_POPULATE_WRITE
  Store* s = static_cast<Store*>(h);
  return madvise(s->base, s->map_size, MADV_POPULATE_WRITE);
#else
  (void)h;
  return -1;
#endif
}

int tstore_contains(void* h, const uint8_t* id) {
  Store* s = static_cast<Store*>(h);
  Guard g(s);
  Slot* slot = find_slot(s, id, false);
  return (slot && slot->state == SLOT_SEALED) ? 1 : 0;
}

uint64_t tstore_used(void* h) { return static_cast<Store*>(h)->hdr->used_bytes; }
uint64_t tstore_capacity(void* h) { return static_cast<Store*>(h)->hdr->arena_size; }
uint64_t tstore_num_objects(void* h) { return static_cast<Store*>(h)->hdr->num_objects; }
uint64_t tstore_evict(void* h, uint64_t need) {
  Store* s = static_cast<Store*>(h);
  Guard g(s);
  return evict_lru(s, need);
}

}  // extern "C"
