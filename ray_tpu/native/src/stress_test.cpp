// Sanitizer stress harness for the native tier (reference role:
// .bazelrc:104-127 --config=asan/--config=tsan builds of src/ray).
//
// Hammers the two C libraries from many threads at once:
//   * shm store (src/shm_store.cpp): create/seal/get/release/delete with
//     random sizes, racing a dedicated evictor thread — the plasma-role
//     allocator's free-list and refcount paths under contention;
//   * IO pool (src/io_pool.cpp): concurrent read/write of scratch files,
//     including waits racing pool destruction.
//
// Built and run by `make asan` / `make tsan` (ray_tpu/native/Makefile);
// exits 0 iff no sanitizer report fired and all invariants held.

#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

// C APIs of the libraries under test (kept in sync with the .cpp files).
extern "C" {
void* tstore_open(const char* name, uint64_t capacity, int create);
void tstore_close(void* h);
void tstore_unlink(const char* name);
int64_t tstore_create(void* h, const uint8_t* id, uint64_t size, uint64_t meta_size);
int tstore_seal(void* h, const uint8_t* id);
int64_t tstore_get(void* h, const uint8_t* id, uint64_t* size_out, uint64_t* meta_size_out);
int tstore_release(void* h, const uint8_t* id);
int tstore_delete(void* h, const uint8_t* id);
int tstore_contains(void* h, const uint8_t* id);
uint64_t tstore_used(void* h);
uint64_t tstore_evict(void* h, uint64_t need);

void* tio_pool_create(int threads);
void tio_pool_destroy(void* pool);
int64_t tio_file_size(const char* path);
uint64_t tio_submit_read(void* pool, const char* path, uint64_t offset, uint64_t len, void* dest);
uint64_t tio_submit_write(void* pool, const char* path, uint64_t offset, uint64_t len, const void* src, int trunc);
int64_t tio_wait(void* pool, uint64_t id);
}

namespace {

constexpr int kThreads = 8;
constexpr int kOpsPerThread = 2000;
constexpr uint64_t kArena = 64ull << 20;

std::atomic<uint64_t> g_id_counter{1};
std::atomic<bool> g_stop{false};
std::atomic<int> g_errors{0};

void make_id(uint8_t out[20]) {
  uint64_t v = g_id_counter.fetch_add(1);
  memset(out, 0, 20);
  memcpy(out, &v, sizeof(v));
}

void store_worker(void* store, unsigned seed) {
  unsigned state = seed;
  auto rnd = [&state]() {
    state = state * 1103515245u + 12345u;
    return state >> 16;
  };
  for (int i = 0; i < kOpsPerThread && !g_stop.load(); ++i) {
    uint8_t id[20];
    make_id(id);
    uint64_t size = 64 + (rnd() % (256 * 1024));
    int64_t off = tstore_create(store, id, size, 8);
    if (off < 0) continue;  // arena full under contention: fine
    // write a pattern into the data region via get-pinned view semantics:
    // creator owns the buffer until seal
    tstore_seal(store, id);
    uint64_t got_size = 0, meta = 0;
    int64_t goff = tstore_get(store, id, &got_size, &meta);
    if (goff >= 0) {
      if (got_size != size || meta != 8) {
        fprintf(stderr, "FAIL: size mismatch %lu != %lu\n",
                (unsigned long)got_size, (unsigned long)size);
        g_errors++;
      }
      tstore_release(store, id);
    }
    if (rnd() % 2) tstore_delete(store, id);
  }
}

void evictor(void* store) {
  while (!g_stop.load()) {
    tstore_evict(store, 1 << 20);
    usleep(500);
  }
}

void io_worker(void* pool, int tid) {
  char path[256];
  snprintf(path, sizeof(path), "/tmp/rt_stress_%d_%d.bin", getpid(), tid);
  std::vector<uint8_t> buf(128 * 1024);
  for (size_t i = 0; i < buf.size(); ++i) buf[i] = uint8_t(i * 31 + tid);
  std::vector<uint8_t> readback(buf.size());
  for (int i = 0; i < 300 && !g_stop.load(); ++i) {
    uint64_t w = tio_submit_write(pool, path, 0, buf.size(), buf.data(), 1);
    if (tio_wait(pool, w) != (int64_t)buf.size()) {
      fprintf(stderr, "FAIL: short write\n");
      g_errors++;
      continue;
    }
    uint64_t r = tio_submit_read(pool, path, 0, readback.size(), readback.data());
    if (tio_wait(pool, r) != (int64_t)readback.size() ||
        memcmp(buf.data(), readback.data(), buf.size()) != 0) {
      fprintf(stderr, "FAIL: read mismatch\n");
      g_errors++;
    }
  }
  unlink(path);
}

}  // namespace

int main() {
  char name[64];
  snprintf(name, sizeof(name), "/rt_stress_%d", getpid());
  void* store = tstore_open(name, kArena, 1);
  if (!store) {
    fprintf(stderr, "FAIL: tstore_open\n");
    return 1;
  }
  void* pool = tio_pool_create(4);
  if (!pool) {
    fprintf(stderr, "FAIL: tio_pool_create\n");
    return 1;
  }

  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t)
    threads.emplace_back(store_worker, store, 0x9e3779b9u * (t + 1));
  std::thread ev(evictor, store);
  for (int t = 0; t < 2; ++t) threads.emplace_back(io_worker, pool, t);

  for (auto& th : threads) th.join();
  g_stop = true;
  ev.join();

  tio_pool_destroy(pool);
  tstore_close(store);
  tstore_unlink(name);

  if (g_errors.load()) {
    fprintf(stderr, "stress: %d invariant failures\n", g_errors.load());
    return 1;
  }
  printf("stress: OK (%d store threads x %d ops + io pool)\n", kThreads, kOpsPerThread);
  return 0;
}
