/* Native hot-path tier: C implementations of the identifier types that sit
 * on every submit/result path (dict keys in the scheduler, refcount, object
 * store and pending-call tables) and of the frame codec the socket loops run.
 *
 * Role parity with the reference's Cython bridge (`python/ray/_raylet.pyx`
 * wrapping `src/ray/common/id.h` BaseID<T> and the task submission hot
 * path): the reference keeps IDs and the submit loop in C++ and lets Python
 * only touch them through Cython; here the runtime is Python-first, so the
 * native tier is inverted — C types that plug into the existing Python
 * runtime.  Semantics mirror ray_tpu/core/ids.py exactly (layouts, nil
 * conventions, counter-minted TaskIDs, put/return index bit).
 *
 * Everything is immutable after construction; the only mutable module state
 * is the two GIL-protected mint counters (task unique, job serial).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <errno.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/random.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#define JOB_ID_SIZE 4
#define ACTOR_UNIQUE_SIZE 8
#define ACTOR_ID_SIZE 12
#define TASK_UNIQUE_SIZE 8
#define TASK_ID_SIZE 20
#define OBJECT_INDEX_SIZE 4
#define OBJECT_ID_SIZE 24
#define NODE_ID_SIZE 16
#define PG_UNIQUE_SIZE 12
#define PG_ID_SIZE 16
#define WORKER_ID_SIZE 16
#define MAX_ID_SIZE 24

/* ------------------------------------------------------------------ */
/* ID object                                                           */
/* ------------------------------------------------------------------ */

typedef struct {
    PyObject_HEAD
    Py_hash_t hash;
    PyObject *bytes; /* owned PyBytes of exactly the type's size */
} IDObject;

typedef struct {
    PyTypeObject type;
    int size;          /* <= 0 marks the abstract base (not instantiable) */
    int kind;          /* mixed into the hash so equal bytes of different
                          kinds don't collide in mixed-key dicts */
    IDObject *nil;     /* cached nil instance (all 0xff) */
} IDType;

/* The base is itself an IDType so classmethods inherited onto it (nil,
 * from_random, ...) can safely cast their `cls` — they reject it via the
 * size sentinel instead of reading past a plain PyTypeObject. */
static IDType BaseID_TypeSpec;
#define BaseID_Type (BaseID_TypeSpec.type)

static inline int
id_check(PyObject *o)
{
    return PyType_IsSubtype(Py_TYPE(o), &BaseID_Type);
}

/* Validate a classmethod's cls: must be one of this module's own static
 * types.  A Python heap subclass of the exported BaseID is NOT an IDType —
 * downcasting it would read type fields past PyTypeObject — and the
 * abstract base itself carries a negative size sentinel. */
static IDType *
concrete_id_type(PyObject *cls)
{
    PyTypeObject *tp = (PyTypeObject *)cls;
    if (tp->tp_flags & Py_TPFLAGS_HEAPTYPE) {
        PyErr_Format(PyExc_TypeError,
                     "%s: id classmethods are not inherited by Python subclasses",
                     tp->tp_name);
        return NULL;
    }
    IDType *t = (IDType *)cls;
    if (t->size <= 0) {
        PyErr_Format(PyExc_TypeError, "%s is abstract; use a concrete id type",
                     tp->tp_name);
        return NULL;
    }
    return t;
}

static inline IDType *
id_type(PyObject *o)
{
    return (IDType *)Py_TYPE(o);
}

static Py_hash_t
mix_hash(PyObject *bytes, int kind)
{
    Py_hash_t h = PyObject_Hash(bytes);
    if (h == -1)
        return -1;
    h ^= (Py_hash_t)kind * (Py_hash_t)0x9e3779b97f4a7c15ULL;
    if (h == -1)
        h = -2;
    return h;
}

/* Build an instance of `cls` from a C buffer (no validation). */
static PyObject *
id_from_buf(PyTypeObject *cls, const char *buf, Py_ssize_t len)
{
    PyObject *bytes = PyBytes_FromStringAndSize(buf, len);
    if (bytes == NULL)
        return NULL;
    IDObject *self = (IDObject *)cls->tp_alloc(cls, 0);
    if (self == NULL) {
        Py_DECREF(bytes);
        return NULL;
    }
    self->bytes = bytes;
    self->hash = mix_hash(bytes, ((IDType *)cls)->kind);
    if (self->hash == -1) {
        Py_DECREF(self);
        return NULL;
    }
    return (PyObject *)self;
}

static PyObject *
id_new(PyTypeObject *cls, PyObject *args, PyObject *kwargs)
{
    PyObject *binary;
    static char *kwlist[] = {"binary", NULL};
    if (!PyArg_ParseTupleAndKeywords(args, kwargs, "O", kwlist, &binary))
        return NULL;
    IDType *t = (IDType *)cls;
    PyObject *bytes;
    if (PyBytes_CheckExact(binary)) {
        bytes = Py_NewRef(binary);
    }
    else {
        bytes = PyBytes_FromObject(binary); /* bytearray/memoryview input */
        if (bytes == NULL)
            return NULL;
    }
    if (PyBytes_GET_SIZE(bytes) != t->size) {
        PyErr_Format(PyExc_ValueError, "%s requires %d bytes, got %zd",
                     cls->tp_name, t->size, PyBytes_GET_SIZE(bytes));
        Py_DECREF(bytes);
        return NULL;
    }
    IDObject *self = (IDObject *)cls->tp_alloc(cls, 0);
    if (self == NULL) {
        Py_DECREF(bytes);
        return NULL;
    }
    self->bytes = bytes;
    self->hash = mix_hash(bytes, t->kind);
    if (self->hash == -1) {
        Py_DECREF(self);
        return NULL;
    }
    return (PyObject *)self;
}

static void
id_dealloc(IDObject *self)
{
    Py_XDECREF(self->bytes);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static Py_hash_t
id_hash(IDObject *self)
{
    return self->hash;
}

static PyObject *
id_richcompare(PyObject *a, PyObject *b, int op)
{
    if (!id_check(a) || !id_check(b)) {
        if (op == Py_EQ)
            Py_RETURN_FALSE;
        if (op == Py_NE)
            Py_RETURN_TRUE;
        Py_RETURN_NOTIMPLEMENTED;
    }
    IDObject *x = (IDObject *)a, *y = (IDObject *)b;
    if (op == Py_EQ || op == Py_NE) {
        int eq = Py_TYPE(a) == Py_TYPE(b) && x->hash == y->hash &&
                 PyBytes_GET_SIZE(x->bytes) == PyBytes_GET_SIZE(y->bytes) &&
                 memcmp(PyBytes_AS_STRING(x->bytes), PyBytes_AS_STRING(y->bytes),
                        (size_t)PyBytes_GET_SIZE(x->bytes)) == 0;
        if (op == Py_NE)
            eq = !eq;
        return PyBool_FromLong(eq);
    }
    /* ordering compares raw bytes, like the Python classes' __lt__ */
    return PyObject_RichCompare(x->bytes, y->bytes, op);
}

static PyObject *
id_binary(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return Py_NewRef(self->bytes);
}

static PyObject *
id_hex(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return PyObject_CallMethod(self->bytes, "hex", NULL);
}

static PyObject *
id_is_nil(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    const char *p = PyBytes_AS_STRING(self->bytes);
    Py_ssize_t n = PyBytes_GET_SIZE(self->bytes);
    for (Py_ssize_t i = 0; i < n; i++) {
        if ((unsigned char)p[i] != 0xff)
            Py_RETURN_FALSE;
    }
    Py_RETURN_TRUE;
}

static const char *
short_name(PyTypeObject *t)
{
    const char *dot = strrchr(t->tp_name, '.');
    return dot ? dot + 1 : t->tp_name;
}

static PyObject *
id_repr(IDObject *self)
{
    PyObject *hex = id_hex(self, NULL);
    if (hex == NULL)
        return NULL;
    PyObject *out = PyUnicode_FromFormat("%s(%U)", short_name(Py_TYPE(self)), hex);
    Py_DECREF(hex);
    return out;
}

static PyObject *
id_reduce(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return Py_BuildValue("(O(O))", Py_TYPE(self), self->bytes);
}

static PyObject *
id_nil(PyObject *cls, PyObject *Py_UNUSED(ignored))
{
    IDType *t = concrete_id_type(cls);
    if (t == NULL)
        return NULL;
    if (t->nil != NULL)
        return Py_NewRef((PyObject *)t->nil);
    char buf[MAX_ID_SIZE];
    memset(buf, 0xff, (size_t)t->size);
    PyObject *inst = id_from_buf((PyTypeObject *)cls, buf, t->size);
    if (inst == NULL)
        return NULL;
    t->nil = (IDObject *)Py_NewRef(inst); /* cached for the module's life */
    return inst;
}

static PyObject *
id_from_random(PyObject *cls, PyObject *Py_UNUSED(ignored))
{
    IDType *t = concrete_id_type(cls);
    if (t == NULL)
        return NULL;
    char buf[MAX_ID_SIZE];
    if (getentropy(buf, (size_t)t->size) != 0)
        return PyErr_SetFromErrno(PyExc_OSError);
    return id_from_buf((PyTypeObject *)cls, buf, t->size);
}

static PyObject *
id_from_hex(PyObject *cls, PyObject *arg)
{
    if (concrete_id_type(cls) == NULL)
        return NULL;
    PyObject *bytes = PyObject_CallMethod((PyObject *)&PyBytes_Type, "fromhex", "O", arg);
    if (bytes == NULL)
        return NULL;
    PyObject *out = PyObject_CallFunctionObjArgs(cls, bytes, NULL);
    Py_DECREF(bytes);
    return out;
}

static PyMethodDef id_methods[] = {
    {"binary", (PyCFunction)id_binary, METH_NOARGS, "Raw bytes of the id."},
    {"hex", (PyCFunction)id_hex, METH_NOARGS, "Hex string of the id."},
    {"is_nil", (PyCFunction)id_is_nil, METH_NOARGS, "True if all-0xff."},
    {"nil", (PyCFunction)id_nil, METH_NOARGS | METH_CLASS, "All-0xff id."},
    {"from_random", (PyCFunction)id_from_random, METH_NOARGS | METH_CLASS,
     "Cryptographically random id."},
    {"from_hex", (PyCFunction)id_from_hex, METH_O | METH_CLASS, "Parse hex."},
    {"__reduce__", (PyCFunction)id_reduce, METH_NOARGS, NULL},
    {NULL, NULL, 0, NULL},
};

static IDType BaseID_TypeSpec = {
    .type = {PyVarObject_HEAD_INIT(NULL, 0)
                 .tp_name = "ray_tpu.core.ids.BaseID",
             .tp_basicsize = sizeof(IDObject),
             .tp_dealloc = (destructor)id_dealloc,
             .tp_repr = (reprfunc)id_repr,
             .tp_hash = (hashfunc)id_hash,
             .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_BASETYPE,
             .tp_doc = "Fixed-width binary identifier. Immutable, hashable, ordered.",
             .tp_richcompare = id_richcompare,
             .tp_methods = id_methods},
    /* abstract: size sentinel rejects inherited classmethods; no tp_new —
       concrete subtypes install id_new */
    .size = -1,
    .kind = 0,
    .nil = NULL,
};

/* ---- mint counters (GIL-protected) -------------------------------- */

/* Starts at a RANDOM 62-bit offset (parity: ids.py _task_counter):
 * worker processes mint task ids locally (fire-and-forget nested
 * submission), and two processes counting from a fixed base collide on
 * their early ids.  Seeded in PyInit. */
static uint64_t task_counter = 2;
static uint64_t job_counter = 0;

static inline void
put_le64(char *dst, uint64_t v)
{
    for (int i = 0; i < 8; i++)
        dst[i] = (char)((v >> (8 * i)) & 0xff);
}

static inline void
put_le32(char *dst, uint32_t v)
{
    for (int i = 0; i < 4; i++)
        dst[i] = (char)((v >> (8 * i)) & 0xff);
}

/* Validated fetch of another id argument's raw bytes. */
static const char *
id_arg_bytes(PyObject *arg, int size, const char *what)
{
    if (!id_check(arg) || PyBytes_GET_SIZE(((IDObject *)arg)->bytes) != size) {
        PyErr_Format(PyExc_TypeError, "expected a %d-byte id for %s", size, what);
        return NULL;
    }
    return PyBytes_AS_STRING(((IDObject *)arg)->bytes);
}

/* ---- JobID -------------------------------------------------------- */

static IDType JobID_Type, NodeID_Type, WorkerID_Type, ActorID_Type,
    TaskID_Type, ObjectID_Type, PlacementGroupID_Type;

static PyObject *
job_from_int(PyObject *cls, PyObject *arg)
{
    uint64_t v = PyLong_AsUnsignedLongLong(arg);
    if (v == (uint64_t)-1 && PyErr_Occurred())
        return NULL;
    if (v >> 32) {
        PyErr_SetString(PyExc_OverflowError, "JobID value exceeds 4 bytes");
        return NULL;
    }
    char buf[JOB_ID_SIZE];
    put_le32(buf, (uint32_t)v);
    return id_from_buf((PyTypeObject *)cls, buf, JOB_ID_SIZE);
}

static PyObject *
job_next(PyObject *cls, PyObject *Py_UNUSED(ignored))
{
    job_counter += 1; /* GIL-atomic */
    char buf[JOB_ID_SIZE];
    put_le32(buf, (uint32_t)job_counter);
    return id_from_buf((PyTypeObject *)cls, buf, JOB_ID_SIZE);
}

static PyObject *
job_ensure_above(PyObject *cls, PyObject *arg)
{
    (void)cls;
    uint64_t v = PyLong_AsUnsignedLongLong(arg);
    if (v == (uint64_t)-1 && PyErr_Occurred())
        return NULL;
    if (v > job_counter)
        job_counter = v;
    Py_RETURN_NONE;
}

static PyObject *
job_int_value(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    const unsigned char *p = (const unsigned char *)PyBytes_AS_STRING(self->bytes);
    uint32_t v = (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
                 ((uint32_t)p[3] << 24);
    return PyLong_FromUnsignedLong(v);
}

static PyMethodDef job_methods[] = {
    {"from_int", (PyCFunction)job_from_int, METH_O | METH_CLASS, NULL},
    {"next", (PyCFunction)job_next, METH_NOARGS | METH_CLASS, NULL},
    {"ensure_above", (PyCFunction)job_ensure_above, METH_O | METH_CLASS,
     "Advance the serial counter past ids restored from a previous process."},
    {"int_value", (PyCFunction)job_int_value, METH_NOARGS, NULL},
    {NULL, NULL, 0, NULL},
};

/* ---- ActorID ------------------------------------------------------ */

static PyObject *
actor_of(PyObject *cls, PyObject *job)
{
    const char *jb = id_arg_bytes(job, JOB_ID_SIZE, "job_id");
    if (jb == NULL)
        return NULL;
    char buf[ACTOR_ID_SIZE];
    if (getentropy(buf, ACTOR_UNIQUE_SIZE) != 0)
        return PyErr_SetFromErrno(PyExc_OSError);
    memcpy(buf + ACTOR_UNIQUE_SIZE, jb, JOB_ID_SIZE);
    return id_from_buf((PyTypeObject *)cls, buf, ACTOR_ID_SIZE);
}

static PyObject *
actor_job_id(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return id_from_buf((PyTypeObject *)&JobID_Type,
                       PyBytes_AS_STRING(self->bytes) + ACTOR_UNIQUE_SIZE, JOB_ID_SIZE);
}

static PyMethodDef actor_methods[] = {
    {"of", (PyCFunction)actor_of, METH_O | METH_CLASS,
     "Random actor id embedding the job id."},
    {"job_id", (PyCFunction)actor_job_id, METH_NOARGS, NULL},
    {NULL, NULL, 0, NULL},
};

/* ---- TaskID ------------------------------------------------------- */

static PyObject *
task_for_normal_task(PyObject *cls, PyObject *job)
{
    const char *jb = id_arg_bytes(job, JOB_ID_SIZE, "job_id");
    if (jb == NULL)
        return NULL;
    char buf[TASK_ID_SIZE];
    put_le64(buf, task_counter++); /* GIL-atomic mint */
    memset(buf + TASK_UNIQUE_SIZE, 0xff, ACTOR_UNIQUE_SIZE);
    memcpy(buf + TASK_UNIQUE_SIZE + ACTOR_UNIQUE_SIZE, jb, JOB_ID_SIZE);
    return id_from_buf((PyTypeObject *)cls, buf, TASK_ID_SIZE);
}

static PyObject *
task_for_actor_task(PyObject *cls, PyObject *actor)
{
    const char *ab = id_arg_bytes(actor, ACTOR_ID_SIZE, "actor_id");
    if (ab == NULL)
        return NULL;
    char buf[TASK_ID_SIZE];
    put_le64(buf, task_counter++);
    memcpy(buf + TASK_UNIQUE_SIZE, ab, ACTOR_ID_SIZE);
    return id_from_buf((PyTypeObject *)cls, buf, TASK_ID_SIZE);
}

static PyObject *
task_for_actor_creation(PyObject *cls, PyObject *actor)
{
    const char *ab = id_arg_bytes(actor, ACTOR_ID_SIZE, "actor_id");
    if (ab == NULL)
        return NULL;
    char buf[TASK_ID_SIZE];
    memset(buf, 0, TASK_UNIQUE_SIZE); /* zero prefix marks creation */
    memcpy(buf + TASK_UNIQUE_SIZE, ab, ACTOR_ID_SIZE);
    return id_from_buf((PyTypeObject *)cls, buf, TASK_ID_SIZE);
}

static PyObject *
task_for_driver(PyObject *cls, PyObject *job)
{
    const char *jb = id_arg_bytes(job, JOB_ID_SIZE, "job_id");
    if (jb == NULL)
        return NULL;
    char buf[TASK_ID_SIZE];
    memset(buf, 0xfe, TASK_UNIQUE_SIZE);
    memset(buf + TASK_UNIQUE_SIZE, 0xff, ACTOR_UNIQUE_SIZE);
    memcpy(buf + TASK_UNIQUE_SIZE + ACTOR_UNIQUE_SIZE, jb, JOB_ID_SIZE);
    return id_from_buf((PyTypeObject *)cls, buf, TASK_ID_SIZE);
}

static PyObject *
task_actor_id(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    const char *embedded = PyBytes_AS_STRING(self->bytes) + TASK_UNIQUE_SIZE;
    int nil_prefix = 1;
    for (int i = 0; i < ACTOR_UNIQUE_SIZE; i++) {
        if ((unsigned char)embedded[i] != 0xff) {
            nil_prefix = 0;
            break;
        }
    }
    if (nil_prefix)
        return id_nil((PyObject *)&ActorID_Type, NULL);
    return id_from_buf((PyTypeObject *)&ActorID_Type, embedded, ACTOR_ID_SIZE);
}

static PyObject *
task_job_id(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return id_from_buf((PyTypeObject *)&JobID_Type,
                       PyBytes_AS_STRING(self->bytes) + TASK_ID_SIZE - JOB_ID_SIZE,
                       JOB_ID_SIZE);
}

static PyMethodDef task_methods[] = {
    {"for_normal_task", (PyCFunction)task_for_normal_task, METH_O | METH_CLASS, NULL},
    {"for_actor_task", (PyCFunction)task_for_actor_task, METH_O | METH_CLASS, NULL},
    {"for_actor_creation", (PyCFunction)task_for_actor_creation, METH_O | METH_CLASS,
     "Deterministic: zero unique prefix marks the creation task."},
    {"for_driver", (PyCFunction)task_for_driver, METH_O | METH_CLASS, NULL},
    {"actor_id", (PyCFunction)task_actor_id, METH_NOARGS, NULL},
    {"job_id", (PyCFunction)task_job_id, METH_NOARGS, NULL},
    {NULL, NULL, 0, NULL},
};

/* ---- ObjectID ----------------------------------------------------- */

static PyObject *
object_for_task_return(PyObject *cls, PyObject *args)
{
    PyObject *task;
    unsigned int index;
    if (!PyArg_ParseTuple(args, "OI", &task, &index))
        return NULL;
    const char *tb = id_arg_bytes(task, TASK_ID_SIZE, "task_id");
    if (tb == NULL)
        return NULL;
    char buf[OBJECT_ID_SIZE];
    memcpy(buf, tb, TASK_ID_SIZE);
    put_le32(buf + TASK_ID_SIZE, index);
    return id_from_buf((PyTypeObject *)cls, buf, OBJECT_ID_SIZE);
}

static PyObject *
object_for_put(PyObject *cls, PyObject *args)
{
    PyObject *task;
    unsigned int put_index;
    if (!PyArg_ParseTuple(args, "OI", &task, &put_index))
        return NULL;
    const char *tb = id_arg_bytes(task, TASK_ID_SIZE, "task_id");
    if (tb == NULL)
        return NULL;
    char buf[OBJECT_ID_SIZE];
    memcpy(buf, tb, TASK_ID_SIZE);
    put_le32(buf + TASK_ID_SIZE, put_index | 0x80000000u);
    return id_from_buf((PyTypeObject *)cls, buf, OBJECT_ID_SIZE);
}

static PyObject *
object_task_id(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return id_from_buf((PyTypeObject *)&TaskID_Type, PyBytes_AS_STRING(self->bytes),
                       TASK_ID_SIZE);
}

static PyObject *
object_job_id(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return id_from_buf((PyTypeObject *)&JobID_Type,
                       PyBytes_AS_STRING(self->bytes) + TASK_ID_SIZE - JOB_ID_SIZE,
                       JOB_ID_SIZE);
}

static inline uint32_t
object_index_raw(IDObject *self)
{
    const unsigned char *p =
        (const unsigned char *)PyBytes_AS_STRING(self->bytes) + TASK_ID_SIZE;
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
}

static PyObject *
object_index(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return PyLong_FromUnsignedLong(object_index_raw(self));
}

static PyObject *
object_is_put(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return PyBool_FromLong((object_index_raw(self) & 0x80000000u) != 0);
}

static PyObject *
object_is_return(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return PyBool_FromLong((object_index_raw(self) & 0x80000000u) == 0);
}

static PyMethodDef object_methods[] = {
    {"for_task_return", (PyCFunction)object_for_task_return, METH_VARARGS | METH_CLASS,
     "index 0 is reserved for puts; returns start at 1 (reference convention)."},
    {"for_put", (PyCFunction)object_for_put, METH_VARARGS | METH_CLASS,
     "puts set the high index bit to avoid collision with returns."},
    {"task_id", (PyCFunction)object_task_id, METH_NOARGS, NULL},
    {"job_id", (PyCFunction)object_job_id, METH_NOARGS, NULL},
    {"index", (PyCFunction)object_index, METH_NOARGS, NULL},
    {"is_put", (PyCFunction)object_is_put, METH_NOARGS, NULL},
    {"is_return", (PyCFunction)object_is_return, METH_NOARGS, NULL},
    {NULL, NULL, 0, NULL},
};

/* ---- PlacementGroupID --------------------------------------------- */

static PyObject *
pg_of(PyObject *cls, PyObject *job)
{
    const char *jb = id_arg_bytes(job, JOB_ID_SIZE, "job_id");
    if (jb == NULL)
        return NULL;
    char buf[PG_ID_SIZE];
    if (getentropy(buf, PG_UNIQUE_SIZE) != 0)
        return PyErr_SetFromErrno(PyExc_OSError);
    memcpy(buf + PG_UNIQUE_SIZE, jb, JOB_ID_SIZE);
    return id_from_buf((PyTypeObject *)cls, buf, PG_ID_SIZE);
}

static PyObject *
pg_job_id(IDObject *self, PyObject *Py_UNUSED(ignored))
{
    return id_from_buf((PyTypeObject *)&JobID_Type,
                       PyBytes_AS_STRING(self->bytes) + PG_UNIQUE_SIZE, JOB_ID_SIZE);
}

static PyMethodDef pg_methods[] = {
    {"of", (PyCFunction)pg_of, METH_O | METH_CLASS, NULL},
    {"job_id", (PyCFunction)pg_job_id, METH_NOARGS, NULL},
    {NULL, NULL, 0, NULL},
};

/* ---- concrete type table ------------------------------------------ */

#define CONCRETE_ID_TYPE(NAME, SIZE, KIND, METHODS)                           \
    {                                                                          \
        .type = {PyVarObject_HEAD_INIT(NULL, 0)                                \
                     .tp_name = "ray_tpu.core.ids." #NAME,                     \
                 .tp_basicsize = sizeof(IDObject),                             \
                 .tp_flags = Py_TPFLAGS_DEFAULT,                               \
                 .tp_new = id_new,                                             \
                 .tp_methods = METHODS},                                       \
        .size = SIZE, .kind = KIND, .nil = NULL,                               \
    }

static IDType JobID_Type = CONCRETE_ID_TYPE(JobID, JOB_ID_SIZE, 1, job_methods);
static IDType NodeID_Type = CONCRETE_ID_TYPE(NodeID, NODE_ID_SIZE, 2, NULL);
static IDType WorkerID_Type = CONCRETE_ID_TYPE(WorkerID, WORKER_ID_SIZE, 3, NULL);
static IDType ActorID_Type = CONCRETE_ID_TYPE(ActorID, ACTOR_ID_SIZE, 4, actor_methods);
static IDType TaskID_Type = CONCRETE_ID_TYPE(TaskID, TASK_ID_SIZE, 5, task_methods);
static IDType ObjectID_Type = CONCRETE_ID_TYPE(ObjectID, OBJECT_ID_SIZE, 6, object_methods);
static IDType PlacementGroupID_Type =
    CONCRETE_ID_TYPE(PlacementGroupID, PG_ID_SIZE, 7, pg_methods);

/* ------------------------------------------------------------------ */
/* Frame codec                                                         */
/* ------------------------------------------------------------------ */
/* The wire unit shared by the worker-pool pipe and the head<->agent rpc
 * plane (runtime/protocol.py): 4-byte LE length + payload.  The decoder
 * owns a growable receive buffer and reads as many frames per recv()
 * syscall as the kernel has buffered — the Python loops pay two syscalls
 * and a chunk-list join per frame.  The GIL is released around every
 * blocking syscall. */

typedef struct {
    PyObject_HEAD
    char *buf;
    Py_ssize_t cap;
    Py_ssize_t start; /* valid bytes live in [start, end) */
    Py_ssize_t end;
} DecoderObject;

#define DECODER_INITIAL_CAP (256 * 1024)
#define DECODER_SHRINK_CAP (4 * 1024 * 1024)
/* Largest frame we will ever reserve for.  Legit frames are pickled RPC
 * messages (bulk objects ride the shm arena / chunked data plane, not one
 * frame), so 1 GiB is far above real traffic while keeping a corrupted
 * 4-byte length header from demanding a ~4 GiB allocation.  Overridable via
 * RAY_TPU_MAX_FRAME_BYTES (read once at module init; the pure-Python codec
 * in runtime/protocol.py honors the same env so the two tiers interop). */
#define DECODER_MAX_FRAME_DEFAULT ((Py_ssize_t)1 << 30)
static Py_ssize_t g_max_frame = DECODER_MAX_FRAME_DEFAULT;
#define DECODER_MAX_FRAME g_max_frame
#define DECODER_MIN_SPARE (64 * 1024)

static PyObject *
decoder_new(PyTypeObject *cls, PyObject *args, PyObject *kwargs)
{
    if ((args && PyTuple_GET_SIZE(args) != 0) || (kwargs && PyDict_GET_SIZE(kwargs) != 0)) {
        PyErr_SetString(PyExc_TypeError, "FrameDecoder takes no arguments");
        return NULL;
    }
    DecoderObject *self = (DecoderObject *)cls->tp_alloc(cls, 0);
    if (self == NULL)
        return NULL;
    self->buf = PyMem_Malloc(DECODER_INITIAL_CAP);
    if (self->buf == NULL) {
        Py_DECREF(self);
        return PyErr_NoMemory();
    }
    self->cap = DECODER_INITIAL_CAP;
    self->start = self->end = 0;
    return (PyObject *)self;
}

static void
decoder_dealloc(DecoderObject *self)
{
    PyMem_Free(self->buf);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static int
decoder_reserve(DecoderObject *self, Py_ssize_t need)
{
    /* ensure `need` contiguous spare bytes after `end` */
    if (self->cap - self->end >= need)
        return 0;
    Py_ssize_t used = self->end - self->start;
    if (self->start > 0) { /* compact first */
        memmove(self->buf, self->buf + self->start, (size_t)used);
        self->start = 0;
        self->end = used;
        if (self->cap - self->end >= need)
            return 0;
    }
    Py_ssize_t newcap = self->cap;
    while (newcap - used < need) {
        if (newcap > PY_SSIZE_T_MAX / 2) {
            PyErr_NoMemory();
            return -1;
        }
        newcap *= 2;
    }
    char *nb = PyMem_Realloc(self->buf, (size_t)newcap);
    if (nb == NULL) {
        PyErr_NoMemory();
        return -1;
    }
    self->buf = nb;
    self->cap = newcap;
    return 0;
}

static inline uint32_t
read_le32(const char *p)
{
    const unsigned char *u = (const unsigned char *)p;
    return (uint32_t)u[0] | ((uint32_t)u[1] << 8) | ((uint32_t)u[2] << 16) |
           ((uint32_t)u[3] << 24);
}

/* Pop one buffered frame as bytes, or NULL without error if incomplete. */
static PyObject *
decoder_pop(DecoderObject *self)
{
    Py_ssize_t have = self->end - self->start;
    if (have < 4)
        return NULL;
    Py_ssize_t len = (Py_ssize_t)read_le32(self->buf + self->start);
    if (have < 4 + len)
        return NULL;
    PyObject *out = PyBytes_FromStringAndSize(self->buf + self->start + 4, len);
    if (out == NULL)
        return NULL;
    self->start += 4 + len;
    if (self->start == self->end) {
        self->start = self->end = 0;
        if (self->cap > DECODER_SHRINK_CAP) {
            /* a giant frame passed through; don't hold its buffer forever */
            char *nb = PyMem_Realloc(self->buf, DECODER_INITIAL_CAP);
            if (nb != NULL) {
                self->buf = nb;
                self->cap = DECODER_INITIAL_CAP;
            }
        }
    }
    return out;
}

static PyObject *
decoder_read_frame(DecoderObject *self, PyObject *arg)
{
    int fd = (int)PyLong_AsLong(arg);
    if (fd == -1 && PyErr_Occurred())
        return NULL;
    for (;;) {
        PyObject *frame = decoder_pop(self);
        if (frame != NULL || PyErr_Occurred())
            return frame;
        /* need more bytes: if the frame length is known, reserve it all so
         * one big payload never loops through doubling reallocs */
        Py_ssize_t have = self->end - self->start;
        Py_ssize_t need = DECODER_MIN_SPARE;
        if (have >= 4) {
            Py_ssize_t len = (Py_ssize_t)read_le32(self->buf + self->start);
            /* sanity-cap BEFORE reserving: a corrupted length header must
             * not demand a multi-GiB allocation */
            if (len > DECODER_MAX_FRAME) {
                PyErr_Format(PyExc_ValueError,
                             "frame length %zd exceeds max %zd (corrupt header?)",
                             len, (Py_ssize_t)DECODER_MAX_FRAME);
                return NULL;
            }
            need = 4 + len - have;
        }
        if (decoder_reserve(self, need < DECODER_MIN_SPARE ? DECODER_MIN_SPARE : need) < 0)
            return NULL;
        Py_ssize_t n;
        Py_BEGIN_ALLOW_THREADS
        n = recv(fd, self->buf + self->end, (size_t)(self->cap - self->end), 0);
        Py_END_ALLOW_THREADS
        if (n == 0) {
            PyErr_SetString(PyExc_ConnectionError, "socket closed");
            return NULL;
        }
        if (n < 0) {
            if (errno == EINTR) {
                if (PyErr_CheckSignals() < 0)
                    return NULL;
                continue;
            }
            return PyErr_SetFromErrno(PyExc_OSError);
        }
        self->end += n;
    }
}

static PyObject *
decoder_pending(DecoderObject *self, PyObject *Py_UNUSED(ignored))
{
    return PyLong_FromSsize_t(self->end - self->start);
}

static PyMethodDef decoder_methods[] = {
    {"read_frame", (PyCFunction)decoder_read_frame, METH_O,
     "read_frame(fd) -> bytes: block until one full frame is available; "
     "raises ConnectionError on EOF."},
    {"pending", (PyCFunction)decoder_pending, METH_NOARGS,
     "Bytes buffered but not yet returned."},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject FrameDecoder_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_hotpath.FrameDecoder",
    .tp_basicsize = sizeof(DecoderObject),
    .tp_dealloc = (destructor)decoder_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "Buffered length-prefixed frame reader over a socket fd.",
    .tp_methods = decoder_methods,
    .tp_new = decoder_new,
};

/* send_frame(fd, payload): writev([le32 length, payload]) with partial-write
 * handling — skips the Python-side header+payload concat copy. */
static PyObject *
hotpath_send_frame(PyObject *Py_UNUSED(mod), PyObject *args)
{
    int fd;
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "iy*", &fd, &view))
        return NULL;
    /* same ceiling the receiving FrameDecoder enforces — a larger frame
     * would be accepted here and then deterministically wedge the peer's
     * connection (the poisoned header stays buffered) */
    if (view.len > DECODER_MAX_FRAME) {
        PyBuffer_Release(&view);
        PyErr_Format(PyExc_OverflowError,
                     "frame length %zd exceeds max %zd",
                     view.len, (Py_ssize_t)DECODER_MAX_FRAME);
        return NULL;
    }
    char hdr[4];
    put_le32(hdr, (uint32_t)view.len);
    Py_ssize_t sent_hdr = 0, sent_body = 0;
    int saved_errno = 0;
    int failed = 0;
    Py_BEGIN_ALLOW_THREADS
    while (sent_hdr < 4 || sent_body < view.len) {
        struct iovec iov[2];
        int iovcnt = 0;
        if (sent_hdr < 4) {
            iov[iovcnt].iov_base = hdr + sent_hdr;
            iov[iovcnt].iov_len = (size_t)(4 - sent_hdr);
            iovcnt++;
        }
        if (sent_body < view.len) {
            iov[iovcnt].iov_base = (char *)view.buf + sent_body;
            iov[iovcnt].iov_len = (size_t)(view.len - sent_body);
            iovcnt++;
        }
        ssize_t n = writev(fd, iov, iovcnt);
        if (n < 0) {
            if (errno == EINTR)
                continue;
            saved_errno = errno;
            failed = 1;
            break;
        }
        if (sent_hdr < 4) {
            Py_ssize_t h = n < 4 - sent_hdr ? n : 4 - sent_hdr;
            sent_hdr += h;
            n -= h;
        }
        sent_body += n;
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&view);
    if (failed) {
        errno = saved_errno;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    Py_RETURN_NONE;
}

static PyMethodDef hotpath_functions[] = {
    {"send_frame", hotpath_send_frame, METH_VARARGS,
     "send_frame(fd, payload): write one length-prefixed frame."},
    {NULL, NULL, 0, NULL},
};

/* ------------------------------------------------------------------ */
/* module                                                              */
/* ------------------------------------------------------------------ */

static struct PyModuleDef hotpath_module = {
    PyModuleDef_HEAD_INIT,
    .m_name = "_hotpath",
    .m_doc = "Native hot-path tier: C id types and frame codec.",
    .m_size = -1,
    .m_methods = hotpath_functions,
};

static int
add_id_type(PyObject *mod, IDType *t, const char *name)
{
    t->type.tp_base = &BaseID_Type;
    if (PyType_Ready(&t->type) < 0)
        return -1;
    PyObject *size = PyLong_FromLong(t->size);
    if (size == NULL)
        return -1;
    int rc = PyDict_SetItemString(t->type.tp_dict, "SIZE", size);
    Py_DECREF(size);
    if (rc < 0)
        return -1;
    PyType_Modified(&t->type);
    return PyModule_AddObjectRef(mod, name, (PyObject *)&t->type);
}

PyMODINIT_FUNC
PyInit__hotpath(void)
{
    if (PyType_Ready(&BaseID_Type) < 0 || PyType_Ready(&FrameDecoder_Type) < 0)
        return NULL;
    {
        uint64_t seed = 0;
        if (getrandom(&seed, sizeof(seed), 0) == (ssize_t)sizeof(seed))
            task_counter = seed >> 2;
    }
    {
        const char *env = getenv("RAY_TPU_MAX_FRAME_BYTES");
        if (env != NULL && env[0] != '\0') {
            char *endp = NULL;
            long long v = strtoll(env, &endp, 10);
            /* uint32 length prefix bounds the wire format at 4 GiB - 1 */
            if (endp != env && *endp == '\0' && v > 0 && v <= 0xffffffffLL)
                g_max_frame = (Py_ssize_t)v;
        }
    }
    PyObject *mod = PyModule_Create(&hotpath_module);
    if (mod == NULL)
        return NULL;
    if (PyModule_AddObjectRef(mod, "FrameDecoder", (PyObject *)&FrameDecoder_Type) < 0) {
        Py_DECREF(mod);
        return NULL;
    }
    if (PyModule_AddObjectRef(mod, "BaseID", (PyObject *)&BaseID_Type) < 0 ||
        add_id_type(mod, &JobID_Type, "JobID") < 0 ||
        add_id_type(mod, &NodeID_Type, "NodeID") < 0 ||
        add_id_type(mod, &WorkerID_Type, "WorkerID") < 0 ||
        add_id_type(mod, &ActorID_Type, "ActorID") < 0 ||
        add_id_type(mod, &TaskID_Type, "TaskID") < 0 ||
        add_id_type(mod, &ObjectID_Type, "ObjectID") < 0 ||
        add_id_type(mod, &PlacementGroupID_Type, "PlacementGroupID") < 0) {
        Py_DECREF(mod);
        return NULL;
    }
    return mod;
}
