"""Loader for the native hot-path extension (``_hotpath.so``).

The extension provides C implementations of the identifier types
(``ray_tpu/core/ids.py`` aliases them when available) and the socket frame
codec.  Role parity: the reference's Cython bridge (``python/ray/_raylet.pyx``
wrapping ``src/ray/common/id.h``) keeps the same objects native.

Builds on first use (``make -s -C ray_tpu/native _hotpath.so``) under a file
lock — worker processes importing concurrently must not race the compiler.
The Makefile writes to a temp name and renames atomically, so a reader can
never dlopen a half-written library.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
# RAY_TPU_HOTPATH_LIB selects an alternate build of the extension — the
# sanitizer leg loads _hotpath_asan.so (built by `make _hotpath_asan.so`)
# with the asan runtime LD_PRELOADed.
_LIB_PATH = os.path.join(_DIR, os.environ.get("RAY_TPU_HOTPATH_LIB", "_hotpath.so"))


_SRC_PATH = os.path.join(_DIR, "src", "hotpath.c")


def _stale() -> bool:
    """True when the binary is missing or older than its source — the same
    staleness make would compute, for two stats instead of a fork/exec on
    every process's import path (workers import this at spawn)."""
    try:
        return os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC_PATH)
    except OSError:
        return True


def _build() -> None:
    import fcntl
    import sys

    lock_path = os.path.join(_DIR, ".hotpath.build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if _stale():  # re-check under the lock: another process built it
                # PYTHON= pins the headers to THIS interpreter's ABI
                subprocess.run(
                    ["make", "-s", "-C", _DIR, f"PYTHON={sys.executable}",
                     os.path.basename(_LIB_PATH)],
                    check=True,
                    capture_output=True,
                )
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _load():
    if _stale():
        try:
            _build()
        except Exception:
            # no toolchain: fall back to an existing binary if one is present
            if not os.path.exists(_LIB_PATH):
                raise
    loader = importlib.machinery.ExtensionFileLoader("_hotpath", _LIB_PATH)
    spec = importlib.util.spec_from_file_location("_hotpath", _LIB_PATH, loader=loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


_mod = _load()

BaseID = _mod.BaseID
JobID = _mod.JobID
NodeID = _mod.NodeID
WorkerID = _mod.WorkerID
ActorID = _mod.ActorID
TaskID = _mod.TaskID
ObjectID = _mod.ObjectID
PlacementGroupID = _mod.PlacementGroupID
FrameDecoder = _mod.FrameDecoder
send_frame = _mod.send_frame
