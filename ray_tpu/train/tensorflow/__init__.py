"""TensorFlow backend: TF_CONFIG-rendezvous'd worker gangs.

Parity: ``python/ray/train/tensorflow/`` — ``TensorflowTrainer`` +
``TensorflowConfig`` (reference ``train/tensorflow/config.py``:
``_setup_tensorflow_environment`` builds the ``TF_CONFIG`` cluster spec from
the worker gang's addresses so ``tf.distribute.MultiWorkerMirroredStrategy``
rendezvouses without its own launcher).

Workers run as PROCESS actors (TF runtime state is per-OS-process, same
reasoning as the torch backend). The trainer allocates one port per rank up
front, builds the shared cluster spec, and each worker exports TF_CONFIG
before the user loop starts — any TF_CONFIG-aware library finds it.
"""

from __future__ import annotations

import json
import socket
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.trainer import DataParallelTrainer

__all__ = ["TensorflowTrainer", "TensorflowConfig", "prepare_dataset_shard"]


@dataclass
class TensorflowConfig:
    """Cluster-spec settings (reference TensorflowConfig)."""

    host: str = "127.0.0.1"


def _free_ports(n: int, host: str):
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()  # freed just before workers bind; races are unlikely
    return ports


def _with_tf_config(fn, cluster_spec: dict):
    """Export TF_CONFIG (cluster + this rank's task) around the user loop."""

    def wrapped(config):
        import inspect
        import os

        from ray_tpu.train import get_context

        rank = get_context().get_world_rank()
        os.environ["TF_CONFIG"] = json.dumps(
            {"cluster": cluster_spec, "task": {"type": "worker", "index": rank}}
        )
        try:
            takes_config = bool(inspect.signature(fn).parameters)
            return fn(config) if takes_config else fn()
        finally:
            os.environ.pop("TF_CONFIG", None)

    return wrapped


class TensorflowTrainer(DataParallelTrainer):
    """Distributed TF trainer (reference TensorflowTrainer): process-actor
    gang with a shared TF_CONFIG cluster spec; the user loop builds its
    ``MultiWorkerMirroredStrategy`` under that spec."""

    _worker_execution = "process"

    def __init__(
        self,
        train_loop_per_worker,
        *,
        tensorflow_config: Optional[TensorflowConfig] = None,
        **kwargs,
    ):
        self.tensorflow_config = tensorflow_config or TensorflowConfig()
        super().__init__(train_loop_per_worker, **kwargs)

    def fit(self):
        host = self.tensorflow_config.host
        n = self.scaling_config.num_workers if self.scaling_config else 1
        ports = _free_ports(n, host)
        cluster = {"worker": [f"{host}:{p}" for p in ports]}
        raw_loop = self.train_loop_per_worker
        self.train_loop_per_worker = _with_tf_config(raw_loop, cluster)
        try:
            return super().fit()
        finally:
            self.train_loop_per_worker = raw_loop


def prepare_dataset_shard(dataset_shard):
    """Passthrough hook (reference prepare_dataset_shard disables TF
    auto-sharding on an already-sharded dataset; our shards arrive
    pre-split from DataConfig)."""
    return dataset_shard
