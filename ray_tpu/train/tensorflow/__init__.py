"""TensorFlow backend: TF_CONFIG-rendezvous'd worker gangs.

Parity: ``python/ray/train/tensorflow/`` — ``TensorflowTrainer`` +
``TensorflowConfig`` (reference ``train/tensorflow/config.py``:
``_setup_tensorflow_environment`` builds the ``TF_CONFIG`` cluster spec from
the worker gang's addresses so ``tf.distribute.MultiWorkerMirroredStrategy``
rendezvouses without its own launcher).

Workers run as PROCESS actors (TF runtime state is per-OS-process, same
reasoning as the torch backend). The trainer allocates one port per rank up
front, builds the shared cluster spec, and each worker exports TF_CONFIG
before the user loop starts — any TF_CONFIG-aware library finds it.
"""

from __future__ import annotations

import json
import socket
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.predictor import Predictor
from ray_tpu.train.trainer import DataParallelTrainer

__all__ = [
    "TensorflowTrainer",
    "TensorflowConfig",
    "TensorflowCheckpoint",
    "TensorflowPredictor",
    "prepare_dataset_shard",
]


@dataclass
class TensorflowConfig:
    """Cluster-spec settings (reference TensorflowConfig)."""

    host: str = "127.0.0.1"


def _free_ports(n: int, host: str):
    from ray_tpu.util.misc import reserve_port

    socks = [reserve_port(host) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()  # freed just before workers bind; held together above so
        # the n reservations are guaranteed distinct
    return ports


def _with_tf_config(fn, cluster_spec: dict):
    """Export TF_CONFIG (cluster + this rank's task) around the user loop."""

    def wrapped(config):
        import inspect
        import os

        from ray_tpu.train import get_context

        rank = get_context().get_world_rank()
        os.environ["TF_CONFIG"] = json.dumps(
            {"cluster": cluster_spec, "task": {"type": "worker", "index": rank}}
        )
        try:
            takes_config = bool(inspect.signature(fn).parameters)
            return fn(config) if takes_config else fn()
        finally:
            os.environ.pop("TF_CONFIG", None)

    return wrapped


class TensorflowTrainer(DataParallelTrainer):
    """Distributed TF trainer (reference TensorflowTrainer): process-actor
    gang with a shared TF_CONFIG cluster spec; the user loop builds its
    ``MultiWorkerMirroredStrategy`` under that spec."""

    _worker_execution = "process"

    def __init__(
        self,
        train_loop_per_worker,
        *,
        tensorflow_config: Optional[TensorflowConfig] = None,
        **kwargs,
    ):
        self.tensorflow_config = tensorflow_config or TensorflowConfig()
        super().__init__(train_loop_per_worker, **kwargs)

    def fit(self):
        host = self.tensorflow_config.host
        n = self.scaling_config.num_workers if self.scaling_config else 1
        ports = _free_ports(n, host)
        cluster = {"worker": [f"{host}:{p}" for p in ports]}
        raw_loop = self.train_loop_per_worker
        self.train_loop_per_worker = _with_tf_config(raw_loop, cluster)
        try:
            return super().fit()
        finally:
            self.train_loop_per_worker = raw_loop


def prepare_dataset_shard(dataset_shard):
    """Passthrough hook (reference prepare_dataset_shard disables TF
    auto-sharding on an already-sharded dataset; our shards arrive
    pre-split from DataConfig)."""
    return dataset_shard


class TensorflowCheckpoint(Checkpoint):
    """A checkpoint holding one saved keras model (parity:
    ``train/tensorflow/tensorflow_checkpoint.py``)."""

    MODEL_FILENAME = "model.keras"

    @classmethod
    def from_model(cls, model, base_dir: Optional[str] = None) -> "TensorflowCheckpoint":
        import os
        import tempfile

        d = base_dir or tempfile.mkdtemp(prefix="tf_ckpt_")
        os.makedirs(d, exist_ok=True)
        model.save(os.path.join(d, cls.MODEL_FILENAME))
        return cls(d)

    def get_model(self):
        import os

        import tensorflow as tf

        return tf.keras.models.load_model(os.path.join(self.path, self.MODEL_FILENAME))


class TensorflowPredictor(Predictor):
    """Batch inference with a keras model (parity:
    ``train/tensorflow/tensorflow_predictor.py``)."""

    def __init__(self, model, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, preprocessor=None) -> "TensorflowPredictor":
        return cls(TensorflowCheckpoint(checkpoint.path).get_model(), preprocessor)

    def _predict_numpy(self, data, **kwargs):
        import numpy as np

        if isinstance(data, dict):
            x = np.stack([np.asarray(v, dtype=np.float32) for v in data.values()], axis=-1)
        else:
            x = np.asarray(data, dtype=np.float32)
        out = self.model(x, training=False)
        return {"predictions": np.asarray(out)}
