"""HuggingFace integrations: Accelerate and Transformers trainers.

Parity: the reference's ``train/huggingface/`` + the Accelerate/DeepSpeed
examples (``train/examples/deepspeed/deepspeed_torch_trainer.py``,
``train/tests/test_torch_accelerate.py``) — a worker gang where each rank
runs under an ``accelerate.Accelerator`` (or a ``transformers.Trainer``),
with the process group and Accelerate's env contract wired by the
framework instead of `accelerate launch`.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from ray_tpu.train.torch import TorchConfig, TorchTrainer


class AccelerateTrainer(TorchTrainer):
    """Runs the user loop under HF Accelerate (parity: AccelerateTrainer).

    The gang's torch process group comes up first (gloo); each worker then
    sets Accelerate's launcher env so ``accelerate.Accelerator()`` adopts
    the existing group instead of spawning its own.
    """

    def __init__(self, train_loop_per_worker, **kwargs):
        def loop(config):
            # rank/world/master env comes from the torch process-group
            # wrapper (_with_process_group); Accelerator() then adopts the
            # already-initialized gloo group — no launcher flag needed.
            os.environ.setdefault("ACCELERATE_USE_CPU", "true")
            return train_loop_per_worker(config)

        super().__init__(loop, **kwargs)


_report_callback_cls = None


def _get_report_callback_cls():
    """Build the TrainerCallback subclass once (lazy: transformers import
    stays off the module-import path). A single cached class keeps
    add_callback/remove_callback(RayTrainReportCallback-style) type
    comparisons working."""
    global _report_callback_cls
    if _report_callback_cls is None:
        from transformers import TrainerCallback

        class RayTrainReportCallbackImpl(TrainerCallback):
            def on_log(self, args, state, control, logs=None, **kwargs):
                from ray_tpu import train

                if logs:
                    metrics = {k: v for k, v in logs.items() if isinstance(v, (int, float))}
                    metrics["step"] = state.global_step
                    metrics["epoch"] = float(state.epoch or 0)
                    train.report(metrics)

        _report_callback_cls = RayTrainReportCallbackImpl
    return _report_callback_cls


def RayTrainReportCallback():
    """transformers.TrainerCallback bridging HF logs to train.report
    (parity: ray.train.huggingface.transformers.RayTrainReportCallback)."""
    return _get_report_callback_cls()()


def prepare_trainer(trainer):
    """Attach the report bridge to a transformers.Trainer (parity:
    transformers.prepare_trainer)."""
    trainer.add_callback(RayTrainReportCallback())
    return trainer


class TransformersTrainer(TorchTrainer):
    """Gang-runs a user-built ``transformers.Trainer`` per worker (parity:
    the legacy TransformersTrainer): ``trainer_init_per_worker(config)``
    returns a Trainer; the framework wires the process group, attaches the
    report callback, and calls ``.train()``."""

    def __init__(self, trainer_init_per_worker: Callable, **kwargs):
        def loop(config):
            hf_trainer = trainer_init_per_worker(config)
            prepare_trainer(hf_trainer)
            hf_trainer.train()

        super().__init__(loop, **kwargs)
