"""HuggingFace integrations: Accelerate and Transformers trainers.

Parity: the reference's ``train/huggingface/`` + the Accelerate/DeepSpeed
examples (``train/examples/deepspeed/deepspeed_torch_trainer.py``,
``train/tests/test_torch_accelerate.py``) — a worker gang where each rank
runs under an ``accelerate.Accelerator`` (or a ``transformers.Trainer``),
with the process group and Accelerate's env contract wired by the
framework instead of `accelerate launch`.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.predictor import Predictor
from ray_tpu.train.torch import TorchConfig, TorchTrainer


class AccelerateTrainer(TorchTrainer):
    """Runs the user loop under HF Accelerate (parity: AccelerateTrainer).

    The gang's torch process group comes up first (gloo); each worker then
    sets Accelerate's launcher env so ``accelerate.Accelerator()`` adopts
    the existing group instead of spawning its own.
    """

    def __init__(self, train_loop_per_worker, **kwargs):
        def loop(config):
            # rank/world/master env comes from the torch process-group
            # wrapper (_with_process_group); Accelerator() then adopts the
            # already-initialized gloo group — no launcher flag needed.
            os.environ.setdefault("ACCELERATE_USE_CPU", "true")
            return train_loop_per_worker(config)

        super().__init__(loop, **kwargs)


_report_callback_cls = None


def _get_report_callback_cls():
    """Build the TrainerCallback subclass once (lazy: transformers import
    stays off the module-import path). A single cached class keeps
    add_callback/remove_callback(RayTrainReportCallback-style) type
    comparisons working."""
    global _report_callback_cls
    if _report_callback_cls is None:
        from transformers import TrainerCallback

        class RayTrainReportCallbackImpl(TrainerCallback):
            def on_log(self, args, state, control, logs=None, **kwargs):
                from ray_tpu import train

                if logs:
                    metrics = {k: v for k, v in logs.items() if isinstance(v, (int, float))}
                    metrics["step"] = state.global_step
                    metrics["epoch"] = float(state.epoch or 0)
                    train.report(metrics)

        _report_callback_cls = RayTrainReportCallbackImpl
    return _report_callback_cls


def RayTrainReportCallback():
    """transformers.TrainerCallback bridging HF logs to train.report
    (parity: ray.train.huggingface.transformers.RayTrainReportCallback)."""
    return _get_report_callback_cls()()


def prepare_trainer(trainer):
    """Attach the report bridge to a transformers.Trainer (parity:
    transformers.prepare_trainer)."""
    trainer.add_callback(RayTrainReportCallback())
    return trainer


class TransformersTrainer(TorchTrainer):
    """Gang-runs a user-built ``transformers.Trainer`` per worker (parity:
    the legacy TransformersTrainer): ``trainer_init_per_worker(config)``
    returns a Trainer; the framework wires the process group, attaches the
    report callback, and calls ``.train()``."""

    def __init__(self, trainer_init_per_worker: Callable, **kwargs):
        def loop(config):
            hf_trainer = trainer_init_per_worker(config)
            prepare_trainer(hf_trainer)
            hf_trainer.train()

        super().__init__(loop, **kwargs)


class TransformersCheckpoint(Checkpoint):
    """A checkpoint holding a ``save_pretrained`` HF model directory
    (parity: ``train/huggingface/transformers/transformers_checkpoint.py``)."""

    @classmethod
    def from_model(cls, model, tokenizer=None, base_dir: Optional[str] = None) -> "TransformersCheckpoint":
        import tempfile

        d = base_dir or tempfile.mkdtemp(prefix="hf_ckpt_")
        os.makedirs(d, exist_ok=True)
        model.save_pretrained(d)
        if tokenizer is not None:
            tokenizer.save_pretrained(d)
        return cls(d)

    def get_model(self, model_cls=None):
        """Reload with ``model_cls.from_pretrained`` (AutoModel default)."""
        if model_cls is None:
            from transformers import AutoModel as model_cls  # noqa: N813
        return model_cls.from_pretrained(self.path)


class TransformersPredictor(Predictor):
    """Batch inference with a HF model or pipeline (parity:
    ``train/huggingface/transformers/transformers_predictor.py``).

    Two modes: a ``transformers.pipeline`` (rows in, list-of-dicts out —
    one DataFrame column per output key), or a bare model whose forward
    consumes ``input_ids`` and yields ``.logits``.
    """

    def __init__(self, model=None, pipeline=None, preprocessor=None):
        super().__init__(preprocessor)
        if model is None and pipeline is None:
            raise ValueError("TransformersPredictor needs a model or a pipeline")
        self.model = model
        self.pipeline = pipeline
        if self.model is not None:
            self.model.eval()

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint: Checkpoint,
        *,
        model_cls=None,
        pipeline_task: Optional[str] = None,
        preprocessor=None,
        **pipeline_kwargs,
    ) -> "TransformersPredictor":
        ckpt = TransformersCheckpoint(checkpoint.path)
        if pipeline_task is not None:
            from transformers import pipeline as hf_pipeline

            return cls(
                pipeline=hf_pipeline(pipeline_task, model=ckpt.path, **pipeline_kwargs),
                preprocessor=preprocessor,
            )
        if model_cls is None:
            # AutoModel would load the HEADLESS base model and silently hand
            # back hidden states as "predictions"; the logits contract needs
            # a headed class by default
            from transformers import AutoModelForCausalLM as model_cls  # noqa: N813
        return cls(model=ckpt.get_model(model_cls), preprocessor=preprocessor)

    def _predict_pandas(self, df, **kwargs):
        import pandas as pd

        if self.pipeline is not None:
            rows = self.pipeline(list(df[df.columns[0]]), **kwargs)
            return pd.DataFrame(rows)
        arrays = {c: df[c].to_numpy() for c in df.columns}
        out = self._predict_numpy(arrays, **kwargs)
        from ray_tpu.train.predictor import wrap_predictions_column

        return pd.DataFrame({k: wrap_predictions_column(v) for k, v in out.items()})

    def _predict_numpy(self, data, **kwargs):
        import numpy as np
        import torch

        if self.pipeline is not None:
            # route dict/array batches through the pandas path's pipeline call
            raise TypeError(
                "pipeline-mode TransformersPredictor takes DataFrame batches "
                "(one text column); pass a model for tensor batches"
            )
        if isinstance(data, dict):
            if "input_ids" in data:
                x = data["input_ids"]
            elif len(data) == 1:
                x = next(iter(data.values()))  # sole column = the token ids
            else:
                raise KeyError(
                    "model-mode TransformersPredictor expects an 'input_ids' "
                    f"column (or a single-column batch); got {sorted(data)}"
                )
        else:
            x = data
        ids = torch.from_numpy(np.asarray(x, dtype=np.int64))
        with torch.no_grad():
            out = self.model(input_ids=ids, **kwargs)
        logits = out.logits if hasattr(out, "logits") else out[0]
        return {"predictions": logits.detach().cpu().numpy()}
