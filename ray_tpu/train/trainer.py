"""Trainers: BaseTrainer → DataParallelTrainer → JaxTrainer.

Parity: ``python/ray/train/base_trainer.py`` + ``data_parallel_trainer.py:25``
(worker-group orchestration, per-framework backends, result/checkpoint
plumbing, FailureConfig restarts) and the Train↔Data wiring of
``_internal/data_config.py``.

TPU-first delta: the flagship backend is JAX — ``ScalingConfig`` becomes a
device mesh, workers are in-process device-pinned actors, and checkpoints
are pytree directories (orbax when available).  The reference's
Torch-process-group rendezvous (``train/torch/config.py:112``) is replaced
by mesh construction; for multi-host, jax.distributed joins hosts into one
global device grid before the gang starts.
"""

from __future__ import annotations

import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayActorError, RayTaskError, WorkerCrashedError
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class Result:
    """What ``Trainer.fit()`` returns (parity: ray.train.Result)."""

    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    metrics_dataframe: List[Dict[str, Any]] = field(default_factory=list)
    error: Optional[BaseException] = None
    # the hyperparameter config that produced this result (parity:
    # ray.air.Result.config — Tune fills it; bare Trainer.fit leaves None)
    config: Optional[Dict[str, Any]] = None

    @property
    def best_checkpoints(self) -> List[Checkpoint]:
        return [self.checkpoint] if self.checkpoint else []


class DataConfig:
    """How Datasets are sharded across workers (parity: data_config.py).

    Default: every dataset in ``datasets`` is materialized and split into
    ``num_workers`` row-balanced shards; each worker sees its shard via
    ``train.get_dataset_shard(name)``.
    """

    def __init__(self, datasets_to_split: Optional[List[str]] = None):
        self._datasets_to_split = datasets_to_split

    def configure(self, datasets: Dict[str, Any], num_workers: int) -> List[Dict[str, Any]]:
        shards: List[Dict[str, Any]] = [{} for _ in range(num_workers)]
        for name, ds in (datasets or {}).items():
            split = self._datasets_to_split is None or name in self._datasets_to_split
            if split and num_workers > 1:
                # Row-balanced, not block-greedy: reference Train shards via
                # streaming_split(equal=True), which splits *blocks* when
                # needed — a single-block dataset must not shard [all, 0].
                mat = ds.materialize() if hasattr(ds, "materialize") else ds
                total = mat.count()
                cuts = [(i * total) // num_workers for i in range(1, num_workers)]
                parts = mat.split_at_indices(cuts)
                for i in range(num_workers):
                    shards[i][name] = parts[i]
            else:
                for i in range(num_workers):
                    shards[i][name] = ds
        return shards


class BaseTrainer:
    _worker_execution = "inproc"  # subclass hook (torch gangs need processes)

    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Adapt this trainer into a Tune trainable (parity: Trainer→Tune).

        Returns a function trainable: Tune merges the search-space config
        into ``train_loop_config`` and the trainer reports through the Tune
        session.
        """
        trainer = self

        def trainable(config: dict):
            import copy

            t = copy.copy(trainer)
            base = dict(getattr(t, "train_loop_config", None) or {})
            base.update(config)
            t.train_loop_config = base
            result = t.fit()
            # Re-report the terminal metrics into the Tune session if active.
            from ray_tpu.tune.session import report as tune_report, in_tune_session

            if in_tune_session() and result.metrics:
                tune_report(result.metrics, checkpoint=result.checkpoint)
            if result.error is not None:
                raise result.error
            return result.metrics

        trainable.__name__ = type(self).__name__
        return trainable


class DataParallelTrainer(BaseTrainer):
    """Runs ``train_loop_per_worker`` on a gang of workers
    (parity: data_parallel_trainer.py:25)."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        dataset_config: Optional[DataConfig] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.dataset_config = dataset_config or DataConfig()

    def training_iterator(self) -> "TrainingIterator":
        """Stream rank-0 reports while the gang trains (one attempt,
        caller-owned loop); ``fit()`` remains the retrying path."""
        return TrainingIterator(self)

    # ------------------------------------------------------------------
    def fit(self) -> Result:
        name = self.run_config.name or f"{type(self).__name__}_{int(time.time())}"
        storage = self.run_config.storage_path or os.path.join(tempfile.gettempdir(), "ray_tpu_results")
        trial_dir = os.path.join(storage, name)
        os.makedirs(trial_dir, exist_ok=True)

        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        latest_checkpoint = self.resume_from_checkpoint
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        best_checkpoint = latest_checkpoint
        error: Optional[BaseException] = None

        while True:
            group = WorkerGroup(
                self.scaling_config, name, trial_dir, execution=self._worker_execution
            )
            group.start()
            shards = self.dataset_config.configure(self.datasets, self.scaling_config.num_workers)
            futures = group.run_async(
                self.train_loop_per_worker, self.train_loop_config, shards, latest_checkpoint
            )
            try:
                # Poll for streamed reports until the gang finishes.
                done_refs: list = []
                pending = list(futures)
                while pending:
                    finished, pending = ray_tpu.wait(pending, num_returns=len(pending), timeout=0.2)
                    # Surface a rank's failure immediately — sibling ranks
                    # blocked in a collective on the dead rank never finish,
                    # so waiting for the full gang would hang fit() forever.
                    ray_tpu.get(finished)
                    done_refs.extend(finished)
                    reports, _ = group.poll_all()
                    for rank, metrics, ckpt in reports:
                        if rank == 0:
                            row = dict(metrics)
                            history.append(row)
                            last_metrics = row
                        if ckpt is not None and rank == 0:
                            best_checkpoint = ckpt
                            latest_checkpoint = ckpt
                # surface worker exceptions
                ray_tpu.get(done_refs)
                reports, _ = group.poll_all()
                for rank, metrics, ckpt in reports:
                    if rank == 0:
                        history.append(dict(metrics))
                        last_metrics = dict(metrics)
                        if ckpt is not None:
                            best_checkpoint = ckpt
                            latest_checkpoint = ckpt
                error = None
                break
            except (RayTaskError, RayActorError, WorkerCrashedError) as exc:
                attempt += 1
                error = exc
                if max_failures != -1 and attempt > max_failures:
                    break
                # restart the gang from the latest checkpoint
            finally:
                group.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=best_checkpoint,
            path=trial_dir,
            metrics_dataframe=history,
            error=error,
        )


class TrainingIterator:
    """Streamed per-report iteration over ONE training-gang run
    (reference: train/trainer.py TrainingIterator — the internal iterator
    fit() drains).  Yields rank-0 report rows as they arrive; ``result()``
    afterwards returns the terminal :class:`Result`.  Unlike ``fit()`` it
    does not retry on failure — the caller owns the loop."""

    def __init__(self, trainer: "DataParallelTrainer"):
        self._trainer = trainer
        self._result: Optional[Result] = None

    def __iter__(self):
        t = self._trainer
        name = t.run_config.name or f"{type(t).__name__}_{int(time.time())}"
        storage = t.run_config.storage_path or os.path.join(tempfile.gettempdir(), "ray_tpu_results")
        trial_dir = os.path.join(storage, name)
        os.makedirs(trial_dir, exist_ok=True)
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        best_checkpoint = t.resume_from_checkpoint
        error: Optional[BaseException] = None
        group = WorkerGroup(t.scaling_config, name, trial_dir, execution=t._worker_execution)
        group.start()

        def drain_rank0():
            # one drain of the group's buffered reports -> rank-0 rows
            reports, _ = group.poll_all()
            for rank, metrics, ckpt in reports:
                if rank != 0:
                    continue
                row = dict(metrics)
                history.append(row)
                nonlocal last_metrics, best_checkpoint
                last_metrics = row
                if ckpt is not None:
                    best_checkpoint = ckpt
                yield row

        try:
            shards = t.dataset_config.configure(t.datasets, t.scaling_config.num_workers)
            futures = group.run_async(
                t.train_loop_per_worker, t.train_loop_config, shards, best_checkpoint
            )
            pending = list(futures)
            done_refs: list = []
            while pending:
                finished, pending = ray_tpu.wait(pending, num_returns=len(pending), timeout=0.2)
                ray_tpu.get(finished)
                done_refs.extend(finished)
                yield from drain_rank0()
            ray_tpu.get(done_refs)
            yield from drain_rank0()
        except (RayTaskError, RayActorError, WorkerCrashedError) as exc:
            error = exc
        finally:
            group.shutdown()
            self._result = Result(
                metrics=last_metrics,
                checkpoint=best_checkpoint,
                path=trial_dir,
                metrics_dataframe=history,
                error=error,
            )
        if error is not None:
            raise error

    def result(self) -> Result:
        if self._result is None:
            raise RuntimeError("iterate the TrainingIterator to completion first")
        return self._result


class JaxTrainer(DataParallelTrainer):
    """The flagship TPU trainer (replaces the reference's TorchTrainer +
    Torch-XLA backend, ``train/torch/xla/config.py:20``): the worker gang
    shares the chip grid, each rank owning a submesh; the user loop builds
    pjit/shard_map programs over ``train.get_context().get_mesh()``."""


# TorchTrainer lives in ray_tpu.train.torch (full gloo process-group
# backend over process-actor gangs); imported at the bottom for the
# historical `ray_tpu.train.trainer.TorchTrainer` path.


from ray_tpu.train.torch import TorchTrainer  # noqa: E402
