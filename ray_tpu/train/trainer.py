"""Trainers: BaseTrainer → DataParallelTrainer → JaxTrainer.

Parity: ``python/ray/train/base_trainer.py`` + ``data_parallel_trainer.py:25``
(worker-group orchestration, per-framework backends, result/checkpoint
plumbing, FailureConfig restarts) and the Train↔Data wiring of
``_internal/data_config.py``.

TPU-first delta: the flagship backend is JAX — ``ScalingConfig`` becomes a
device mesh, workers are in-process device-pinned actors, and checkpoints
are pytree directories (orbax when available).  The reference's
Torch-process-group rendezvous (``train/torch/config.py:112``) is replaced
by mesh construction; for multi-host, jax.distributed joins hosts into one
global device grid before the gang starts.
"""

from __future__ import annotations

import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayActorError, RayTaskError, WorkerCrashedError
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class Result:
    """What ``Trainer.fit()`` returns (parity: ray.train.Result)."""

    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    metrics_dataframe: List[Dict[str, Any]] = field(default_factory=list)
    error: Optional[BaseException] = None
    # the hyperparameter config that produced this result (parity:
    # ray.air.Result.config — Tune fills it; bare Trainer.fit leaves None)
    config: Optional[Dict[str, Any]] = None

    @property
    def best_checkpoints(self) -> List[Checkpoint]:
        return [self.checkpoint] if self.checkpoint else []


class DataConfig:
    """How Datasets are sharded across workers (parity: data_config.py).

    Default: every dataset in ``datasets`` is materialized and split into
    ``num_workers`` row-balanced shards; each worker sees its shard via
    ``train.get_dataset_shard(name)``.
    """

    def __init__(self, datasets_to_split: Optional[List[str]] = None):
        self._datasets_to_split = datasets_to_split

    def configure(self, datasets: Dict[str, Any], num_workers: int) -> List[Dict[str, Any]]:
        shards: List[Dict[str, Any]] = [{} for _ in range(num_workers)]
        for name, ds in (datasets or {}).items():
            split = self._datasets_to_split is None or name in self._datasets_to_split
            if split and num_workers > 1:
                # Row-balanced, not block-greedy: reference Train shards via
                # streaming_split(equal=True), which splits *blocks* when
                # needed — a single-block dataset must not shard [all, 0].
                mat = ds.materialize() if hasattr(ds, "materialize") else ds
                total = mat.count()
                cuts = [(i * total) // num_workers for i in range(1, num_workers)]
                parts = mat.split_at_indices(cuts)
                for i in range(num_workers):
                    shards[i][name] = parts[i]
            else:
                for i in range(num_workers):
                    shards[i][name] = ds
        return shards


class BaseTrainer:
    _worker_execution = "inproc"  # subclass hook (torch gangs need processes)

    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Adapt this trainer into a Tune trainable (parity: Trainer→Tune).

        Returns a function trainable: Tune merges the search-space config
        into ``train_loop_config`` and the trainer reports through the Tune
        session.
        """
        trainer = self

        def trainable(config: dict):
            import copy

            t = copy.copy(trainer)
            base = dict(getattr(t, "train_loop_config", None) or {})
            base.update(config)
            t.train_loop_config = base
            result = t.fit()
            # Re-report the terminal metrics into the Tune session if active.
            from ray_tpu.tune.session import report as tune_report, in_tune_session

            if in_tune_session() and result.metrics:
                tune_report(result.metrics, checkpoint=result.checkpoint)
            if result.error is not None:
                raise result.error
            return result.metrics

        trainable.__name__ = type(self).__name__
        return trainable


class DataParallelTrainer(BaseTrainer):
    """Runs ``train_loop_per_worker`` on a gang of workers
    (parity: data_parallel_trainer.py:25)."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        dataset_config: Optional[DataConfig] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.dataset_config = dataset_config or DataConfig()

    def training_iterator(self, *, auto_repair: bool = False) -> "TrainingIterator":
        """Stream rank-0 reports while the gang trains (caller-owned loop);
        ``fit()`` remains the batch path.  ``auto_repair=True`` restarts the
        gang from the best checkpoint on a worker death instead of raising."""
        return TrainingIterator(self, auto_repair=auto_repair)

    # ------------------------------------------------------------------
    def fit(self) -> Result:
        name = self.run_config.name or f"{type(self).__name__}_{int(time.time())}"
        storage = self.run_config.storage_path or os.path.join(tempfile.gettempdir(), "ray_tpu_results")
        trial_dir = os.path.join(storage, name)
        os.makedirs(trial_dir, exist_ok=True)

        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        latest_checkpoint = self.resume_from_checkpoint
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        best_checkpoint = latest_checkpoint
        error: Optional[BaseException] = None

        while True:
            group = WorkerGroup(
                self.scaling_config,
                name,
                trial_dir,
                execution=self._worker_execution,
                restart_count=attempt,
            )
            group.start()
            shards = self.dataset_config.configure(self.datasets, self.scaling_config.num_workers)
            futures = group.run_async(
                self.train_loop_per_worker, self.train_loop_config, shards, latest_checkpoint
            )
            try:
                # Poll for streamed reports until the gang finishes.
                done_refs: list = []
                pending = list(futures)
                while pending:
                    finished, pending = ray_tpu.wait(pending, num_returns=len(pending), timeout=0.2)
                    # Surface a rank's failure immediately — sibling ranks
                    # blocked in a collective on the dead rank never finish,
                    # so waiting for the full gang would hang fit() forever.
                    if not finished:
                        dead = group.dead_workers()
                        if dead:
                            raise dead[0][1]
                    ray_tpu.get(finished)
                    done_refs.extend(finished)
                    reports, _ = group.poll_all()
                    for rank, metrics, ckpt in reports:
                        if rank == 0:
                            row = dict(metrics)
                            history.append(row)
                            last_metrics = row
                        if ckpt is not None and rank == 0:
                            best_checkpoint = ckpt
                            latest_checkpoint = ckpt
                # surface worker exceptions
                ray_tpu.get(done_refs)
                reports, _ = group.poll_all()
                for rank, metrics, ckpt in reports:
                    if rank == 0:
                        history.append(dict(metrics))
                        last_metrics = dict(metrics)
                        if ckpt is not None:
                            best_checkpoint = ckpt
                            latest_checkpoint = ckpt
                error = None
                break
            except (RayTaskError, RayActorError, WorkerCrashedError) as exc:
                attempt += 1
                error = exc
                if max_failures != -1 and attempt > max_failures:
                    break
                # restart the gang from the latest checkpoint
            finally:
                group.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=best_checkpoint,
            path=trial_dir,
            metrics_dataframe=history,
            error=error,
        )


class TrainingIterator:
    """Streamed per-report iteration over a training-gang run
    (reference: train/trainer.py TrainingIterator — the internal iterator
    fit() drains).  Yields rank-0 report rows as they arrive; ``result()``
    afterwards returns the terminal :class:`Result`.

    Fault contract: a gang member that dies mid-step (``kill -9`` included)
    surfaces as the **typed** error — ``ActorDiedError`` /
    ``WorkerCrashedError`` — never a hang.  The rank-0 drain loop probes the
    control plane's actor table between waits, so a rank whose run future
    can no longer resolve is converted to its typed death immediately.
    With ``auto_repair=True`` the death instead restarts the gang from the
    best checkpoint seen so far (repair budget:
    ``run_config.failure_config.max_failures``, 0 meaning a small default);
    otherwise the typed error is raised to the caller."""

    def __init__(self, trainer: "DataParallelTrainer", *, auto_repair: bool = False):
        self._trainer = trainer
        self._auto_repair = auto_repair
        self._result: Optional[Result] = None

    def __iter__(self):
        t = self._trainer
        name = t.run_config.name or f"{type(t).__name__}_{int(time.time())}"
        storage = t.run_config.storage_path or os.path.join(tempfile.gettempdir(), "ray_tpu_results")
        trial_dir = os.path.join(storage, name)
        os.makedirs(trial_dir, exist_ok=True)
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        best_checkpoint = t.resume_from_checkpoint
        error: Optional[BaseException] = None
        max_failures = t.run_config.failure_config.max_failures
        repairs_left = (max_failures if max_failures > 0 else 3) if max_failures != -1 else -1

        attempt = 0
        try:
            while True:
                group = WorkerGroup(
                    t.scaling_config,
                    name,
                    trial_dir,
                    execution=t._worker_execution,
                    restart_count=attempt,
                )
                group.start()

                def drain_rank0(group=group):
                    # one drain of the group's buffered reports -> rank-0 rows
                    reports, _ = group.poll_all()
                    for rank, metrics, ckpt in reports:
                        if rank != 0:
                            continue
                        row = dict(metrics)
                        history.append(row)
                        nonlocal last_metrics, best_checkpoint
                        last_metrics = row
                        if ckpt is not None:
                            best_checkpoint = ckpt
                        yield row

                try:
                    shards = t.dataset_config.configure(
                        t.datasets, t.scaling_config.num_workers
                    )
                    futures = group.run_async(
                        t.train_loop_per_worker, t.train_loop_config, shards, best_checkpoint
                    )
                    pending = list(futures)
                    done_refs: list = []
                    while pending:
                        finished, pending = ray_tpu.wait(
                            pending, num_returns=len(pending), timeout=0.2
                        )
                        if not finished:
                            # Liveness guard: a DEAD rank whose future is
                            # still pending (siblings blocked on it in a
                            # collective) must raise typed, not hang.
                            dead = group.dead_workers()
                            if dead:
                                raise dead[0][1]
                        ray_tpu.get(finished)
                        done_refs.extend(finished)
                        yield from drain_rank0()
                    ray_tpu.get(done_refs)
                    yield from drain_rank0()
                    error = None
                    break
                except (RayTaskError, RayActorError, WorkerCrashedError) as exc:
                    error = exc
                    if not self._auto_repair:
                        break
                    if repairs_left == 0:
                        break
                    if repairs_left > 0:
                        repairs_left -= 1
                    attempt += 1
                    # repair: restart the gang from the best checkpoint
                finally:
                    group.shutdown()
        finally:
            self._result = Result(
                metrics=last_metrics,
                checkpoint=best_checkpoint,
                path=trial_dir,
                metrics_dataframe=history,
                error=error,
            )
        if error is not None:
            raise error

    def result(self) -> Result:
        if self._result is None:
            raise RuntimeError("iterate the TrainingIterator to completion first")
        return self._result


class JaxTrainer(DataParallelTrainer):
    """The flagship TPU trainer (replaces the reference's TorchTrainer +
    Torch-XLA backend, ``train/torch/xla/config.py:20``): the worker gang
    shares the chip grid, each rank owning a submesh; the user loop builds
    pjit/shard_map programs over ``train.get_context().get_mesh()``.

    Two modes:

    * **user-loop mode** (``train_loop_per_worker`` given): the classic
      DataParallelTrainer path — the loop runs on every rank of a
      :class:`WorkerGroup` gang.
    * **gang mode** (``train_loop_per_worker=None`` and ``gang=dict(...)``):
      the data-parallel step compiles to a plan whose training stage is a
      ``StageGroup`` gang driven by a
      :class:`~ray_tpu.train.controller.TrainController` — repairable
      (member death → BROKEN → repair, bit-exact resume from the latest
      step checkpoint), elastic (autoscaler grow/shrink), and preemptible
      by serving bursts.  ``gang`` keys are TrainController kwargs
      (``world_size``, ``batch_size``, ``feature_dim``, ``seed``, ...)
      plus an optional ``num_steps``; a ``datasets={"train": ds}`` entry
      feeds the gang from the streaming Dataset executor.  Whether a
      mid-run member death auto-repairs follows
      ``run_config.failure_config.max_failures`` (0 → the typed error
      propagates into ``Result.error``).  The controller stays alive after
      ``fit()`` as ``self.controller`` for status/resize/shutdown.
    """

    def __init__(
        self,
        train_loop_per_worker: Optional[Callable] = None,
        *,
        gang: Optional[dict] = None,
        num_steps: Optional[int] = None,
        **kwargs,
    ):
        if train_loop_per_worker is None and gang is None:
            raise ValueError(
                "JaxTrainer needs either train_loop_per_worker (user-loop "
                "mode) or gang=dict(...) (compiled StageGroup gang mode)"
            )
        self.gang = dict(gang) if gang is not None else None
        self.num_steps = num_steps
        self.controller = None  # set by gang-mode fit()
        super().__init__(train_loop_per_worker, **kwargs)

    def fit(self) -> Result:
        if self.train_loop_per_worker is not None:
            return super().fit()
        from ray_tpu.train.controller import TrainController

        name = self.run_config.name or f"JaxTrainer_{int(time.time())}"
        spec = dict(self.gang or {})
        num_steps = int(
            self.num_steps
            if self.num_steps is not None
            else spec.pop("num_steps", 10)
        )
        spec.pop("num_steps", None)
        if self.datasets and "dataset" not in spec:
            spec["dataset"] = self.datasets.get("train")
        ctl = self.controller = TrainController(name, **spec)
        auto_repair = self.run_config.failure_config.max_failures != 0
        error: Optional[BaseException] = None
        try:
            ctl.run(num_steps, auto_repair=auto_repair)
            ctl.save_checkpoint()
        except (RayTaskError, RayActorError, WorkerCrashedError) as exc:
            error = exc
        losses = ctl.losses()
        ckpt_dir = os.path.dirname(ctl.checkpoint_path)
        return Result(
            metrics={
                "step": ctl.step_count,
                "loss": losses[-1] if losses else None,
                "world_size": ctl.world_size,
            },
            checkpoint=Checkpoint(ckpt_dir) if ctl.last_checkpoint else None,
            path=ckpt_dir,
            metrics_dataframe=[
                {"step": i + 1, "loss": loss} for i, loss in enumerate(losses)
            ],
            error=error,
        )


# TorchTrainer lives in ray_tpu.train.torch (full gloo process-group
# backend over process-actor gangs); imported at the bottom for the
# historical `ray_tpu.train.trainer.TorchTrainer` path.


from ray_tpu.train.torch import TorchTrainer  # noqa: E402
