"""Torch backend: distributed torch training over a process-actor gang.

Parity: ``python/ray/train/torch/`` — ``TorchTrainer``, ``TorchConfig``
(``config.py:112``: rank-0 address broadcast + ``dist.init_process_group``),
``prepare_model`` (DDP wrap, ``train_loop_utils.py:158``) and
``prepare_data_loader`` (DistributedSampler injection).

Design note: jax gangs run as in-process actors sharing the chip grid, but a
torch process group is per-OS-process global state, so Torch gangs run as
PROCESS actors; the trainer picks a free TCP port up front and every rank
joins a gloo group over it before the user loop starts.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.predictor import Predictor
from ray_tpu.train.trainer import DataParallelTrainer

__all__ = [
    "TorchTrainer",
    "TorchConfig",
    "TorchCheckpoint",
    "TorchPredictor",
    "prepare_model",
    "prepare_data_loader",
    "get_device",
]


@dataclass
class TorchConfig:
    """Process-group settings (reference TorchConfig, train/torch/config.py)."""

    backend: str = "gloo"
    init_method: str = "tcp"
    timeout_s: int = 1800


def _free_port() -> int:
    from ray_tpu.util.misc import free_port

    return free_port()


def _with_process_group(fn, backend: str, master_addr: str, master_port: int, timeout_s: int):
    """Wrap the user loop: join the gloo world before, tear down after.
    Rank/world come from the train session (the wrapper runs inside the
    worker after init_session)."""

    def wrapped(config):
        import datetime
        import inspect

        import torch.distributed as dist

        from ray_tpu.train import get_context

        ctx = get_context()
        # Torch-launcher env contract (reference TorchConfig sets the same):
        # libraries that re-derive the rendezvous from env (HF Accelerate,
        # lightning) find it without their own launcher.
        import os

        os.environ["MASTER_ADDR"] = master_addr
        os.environ["MASTER_PORT"] = str(master_port)
        os.environ["RANK"] = str(ctx.get_world_rank())
        os.environ["LOCAL_RANK"] = str(ctx.get_local_rank())
        os.environ["WORLD_SIZE"] = str(ctx.get_world_size())
        os.environ["LOCAL_WORLD_SIZE"] = str(ctx.get_local_world_size())
        os.environ["NODE_RANK"] = str(ctx.get_node_rank())
        created_group = False
        if not dist.is_initialized():  # loops that rendezvous themselves keep working
            dist.init_process_group(
                backend=backend,
                init_method=f"tcp://{master_addr}:{master_port}",
                rank=ctx.get_world_rank(),
                world_size=ctx.get_world_size(),
                timeout=datetime.timedelta(seconds=timeout_s),
            )
            created_group = True
        try:
            takes_config = bool(inspect.signature(fn).parameters)
            return fn(config) if takes_config else fn()
        finally:
            if created_group:
                try:
                    dist.destroy_process_group()
                except Exception:
                    pass

    return wrapped


class TorchTrainer(DataParallelTrainer):
    """Distributed torch trainer (reference TorchTrainer): the worker gang
    runs in separate processes, wired into one ``torch.distributed`` gloo
    group; ``prepare_model`` adds DDP gradient sync."""

    _worker_execution = "process"

    def __init__(
        self,
        train_loop_per_worker,
        *,
        torch_config: Optional[TorchConfig] = None,
        **kwargs,
    ):
        self.torch_config = torch_config or TorchConfig()
        super().__init__(train_loop_per_worker, **kwargs)

    def fit(self):
        # fresh port per fit: gloo leaves TIME_WAIT sockets behind
        port = _free_port()
        raw_loop = self.train_loop_per_worker
        self.train_loop_per_worker = _with_process_group(
            raw_loop,
            self.torch_config.backend,
            "127.0.0.1",
            port,
            self.torch_config.timeout_s,
        )
        try:
            return super().fit()
        finally:
            self.train_loop_per_worker = raw_loop


def get_device():
    """The torch device for this worker (reference train.torch.get_device)."""
    import torch

    return torch.device("cpu")  # TPU compute runs through jax; torch is host-side


def prepare_model(model, *, parallel_strategy: str = "ddp"):
    """Wrap for gradient sync (reference prepare_model): DDP when the
    process group is up and world_size > 1, identity otherwise."""
    import torch.distributed as dist

    if parallel_strategy and dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Re-build a DataLoader with a DistributedSampler so each rank sees its
    shard (reference prepare_data_loader)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1):
        return data_loader
    from torch.utils.data import SequentialSampler

    shuffle = not isinstance(data_loader.sampler, SequentialSampler)
    sampler = DistributedSampler(data_loader.dataset, shuffle=shuffle)
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
        pin_memory=data_loader.pin_memory,
        worker_init_fn=data_loader.worker_init_fn,
        generator=data_loader.generator,
    )


class TorchCheckpoint(Checkpoint):
    """A checkpoint holding a torch module's ``state_dict`` (parity:
    ``train/torch/torch_checkpoint.py``)."""

    MODEL_FILENAME = "model.pt"

    @classmethod
    def from_model(cls, model, base_dir: Optional[str] = None) -> "TorchCheckpoint":
        return cls.from_state_dict(model.state_dict(), base_dir)

    @classmethod
    def from_state_dict(cls, state_dict, base_dir: Optional[str] = None) -> "TorchCheckpoint":
        import tempfile

        import torch

        d = base_dir or tempfile.mkdtemp(prefix="torch_ckpt_")
        os.makedirs(d, exist_ok=True)
        torch.save(state_dict, os.path.join(d, cls.MODEL_FILENAME))
        return cls(d)

    def get_model(self, model):
        """Load the stored state dict into ``model`` and return it."""
        import torch

        state = torch.load(
            os.path.join(self.path, self.MODEL_FILENAME), weights_only=True
        )
        model.load_state_dict(state)
        model.eval()
        return model


class TorchPredictor(Predictor):
    """Batch inference with a torch module (parity:
    ``train/torch/torch_predictor.py``).  Dict batches stack their feature
    columns along the last axis; outputs come back as numpy."""

    def __init__(self, model, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model
        self.model.eval()

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, model, preprocessor=None) -> "TorchPredictor":
        return cls(TorchCheckpoint(checkpoint.path).get_model(model), preprocessor)

    def _predict_numpy(self, data, **kwargs):
        import numpy as np
        import torch

        if isinstance(data, dict):
            x = np.stack([np.asarray(v, dtype=np.float32) for v in data.values()], axis=-1)
        else:
            x = np.asarray(data, dtype=np.float32)
        with torch.no_grad():
            out = self.model(torch.from_numpy(x), **kwargs)
        return {"predictions": out.detach().cpu().numpy()}
