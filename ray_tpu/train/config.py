"""Shared Train/Tune configuration objects.

Parity: ``python/ray/air/config.py:103`` (``ScalingConfig``, ``RunConfig``,
``CheckpointConfig``, ``FailureConfig``) — the AIR-common config surface the
reference shares between Train and Tune.

TPU-first delta: ``ScalingConfig`` maps directly to a ``jax.sharding.Mesh``
specification (workers × devices-per-worker over the device grid) instead of
to placement-group bundles of GPUs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many workers × what resources each (reference: config.py:103).

    ``num_workers`` data-parallel workers; each holds ``num_devices_per_worker``
    TPU devices (the mesh's model-parallel submesh when >1).
    """

    num_workers: int = 1
    use_tpu: bool = False
    num_devices_per_worker: int = 1
    resources_per_worker: Optional[Dict[str, float]] = None
    trainer_resources: Optional[Dict[str, float]] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1}
        if self.use_tpu:
            res["TPU"] = self.num_devices_per_worker
        return res

    @property
    def total_devices(self) -> int:
        return self.num_workers * self.num_devices_per_worker


@dataclass
class FailureConfig:
    """Worker-group fault tolerance (reference: FailureConfig).

    max_failures: restarts of the whole worker group before giving up;
    -1 = unlimited.
    """

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 0
    # tune experiment callbacks (air/integrations loggers plug in here)
    callbacks: Optional[list] = None
