"""Shared Train/Tune configuration objects.

Parity: ``python/ray/air/config.py:103`` (``ScalingConfig``, ``RunConfig``,
``CheckpointConfig``, ``FailureConfig``) — the AIR-common config surface the
reference shares between Train and Tune.

TPU-first delta: ``ScalingConfig`` maps directly to a ``jax.sharding.Mesh``
specification (workers × devices-per-worker over the device grid) instead of
to placement-group bundles of GPUs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


#: Key under which a trainer's primary dataset is passed/looked up
#: (reference: air/constants.py TRAIN_DATASET_KEY).
TRAIN_DATASET_KEY = "train"


@dataclass
class SyncConfig:
    """Driver<->storage sync knobs (reference: _internal/syncer.py
    SyncConfig).  On this runtime, checkpoints/artifacts write straight to
    ``RunConfig.storage_path`` (orbax/posix IO) — there is no separate
    sync daemon — so these fields gate only whether trial artifacts are
    mirrored at all."""

    sync_period: float = 300.0
    sync_timeout: float = 1800.0
    sync_artifacts: bool = False


class BackendConfig:
    """Parent class for training-backend configurations (reference:
    train/backend.py).  Concrete backends: the torch(gloo)/tf/jax trainer
    setups in ``ray_tpu/train/trainer.py`` — subclass and override
    ``backend_name`` for custom setups."""

    @property
    def backend_name(self) -> str:
        return type(self).__name__.replace("Config", "").lower() or "custom"


@dataclass
class ScalingConfig:
    """How many workers × what resources each (reference: config.py:103).

    ``num_workers`` data-parallel workers; each holds ``num_devices_per_worker``
    TPU devices (the mesh's model-parallel submesh when >1).
    """

    num_workers: int = 1
    use_tpu: bool = False
    num_devices_per_worker: int = 1
    resources_per_worker: Optional[Dict[str, float]] = None
    trainer_resources: Optional[Dict[str, float]] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1}
        if self.use_tpu:
            res["TPU"] = self.num_devices_per_worker
        return res

    @property
    def total_devices(self) -> int:
        return self.num_workers * self.num_devices_per_worker


@dataclass
class FailureConfig:
    """Worker-group fault tolerance (reference: FailureConfig).

    max_failures: restarts of the whole worker group before giving up;
    -1 = unlimited.
    """

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 0
    # tune experiment callbacks (air/integrations loggers plug in here)
    callbacks: Optional[list] = None
    # stop criteria for Tune trials: {"metric": threshold} dict or a
    # tune.Stopper (reference puts stop on air.RunConfig the same way)
    stop: Optional[Any] = None
    sync_config: Optional["SyncConfig"] = None
