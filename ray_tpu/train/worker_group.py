"""WorkerGroup: the gang of training-worker actors.

Parity: ``python/ray/train/_internal/worker_group.py:102`` (actor group with
``execute``/``execute_async``) + ``_internal/backend_executor.py:66``
(start, rendezvous, start_training, fault handling).

TPU-first delta: workers are **device-pinned in-process actors** — JAX is a
single-controller SPMD runtime, so the training gang lives in the driver
process as threads, each owning a slice of the device grid (its submesh).
Multi-host scale-out replicates this gang per host over jax.distributed;
the gRPC worker-process indirection of the reference's GPU path would force
host↔device copies on every collective and is deliberately absent.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.exceptions import RayActorError, RayTaskError, WorkerCrashedError
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, _Session, init_session, shutdown_session


@ray_tpu.remote
class TrainWorkerActor:
    """One rank of the training gang (parity: worker_group.py RayTrainWorker)."""

    def __init__(
        self,
        rank: int,
        world_size: int,
        devices_per_worker: int,
        experiment_name: str,
        trial_dir: str,
        pin_devices: bool = True,
        group_token: str = "",
        restart_count: int = 0,
    ):
        self.rank = rank
        self.world_size = world_size
        self.experiment_name = experiment_name
        self.trial_dir = trial_dir
        self._reports: List[Tuple[dict, Any]] = []
        self._reports_lock = threading.Lock()
        self._done = False
        self._error: Optional[BaseException] = None
        self._result: Any = None

        # Process-actor gangs (torch) must NOT touch jax here: on a real TPU
        # host libtpu is single-process-exclusive, and a second rank's
        # jax.devices() would fail or block waiting for the chip lock.
        if pin_devices:
            import jax

            all_devices = jax.devices()
        else:
            all_devices = []
        n = min(devices_per_worker, len(all_devices))
        lo = (rank * n) % max(len(all_devices), 1)
        # Wrap around so every rank gets exactly n devices even when the
        # gang oversubscribes the grid (CPU-mesh tests); on real slices the
        # ScalingConfig is expected to tile the grid evenly.
        self.devices = [all_devices[(lo + i) % len(all_devices)] for i in range(n)] if all_devices else []
        mesh = None
        if self.devices:
            import numpy as np
            from jax.sharding import Mesh

            mesh = Mesh(np.asarray(self.devices).reshape(-1), ("data",))
        self.context = TrainContext(
            world_rank=rank,
            world_size=world_size,
            local_rank=rank,
            local_world_size=world_size,
            experiment_name=experiment_name,
            trial_dir=trial_dir,
            devices=list(self.devices),
            mesh=mesh,
            group_token=group_token,
            restart_count=restart_count,
        )

    # ------------------------------------------------------------ running
    def run(self, fn: Callable, config: dict, dataset_shards: dict, latest_checkpoint) -> Any:
        def reporter(rank, metrics, checkpoint):
            with self._reports_lock:
                self._reports.append((metrics, checkpoint))

        init_session(_Session(self.context, reporter, dataset_shards, latest_checkpoint))
        try:
            import inspect

            takes_config = bool(inspect.signature(fn).parameters)
            result = fn(config or {}) if takes_config else fn()
            self._result = result
            return result
        except BaseException as exc:  # noqa: BLE001
            self._error = exc
            raise
        finally:
            self._done = True
            shutdown_session()

    # ------------------------------------------------------------ polling
    def poll(self) -> Tuple[List[Tuple[dict, Any]], bool]:
        """Drain buffered (metrics, checkpoint) reports; returns (reports, done)."""
        with self._reports_lock:
            out, self._reports = self._reports, []
        return out, self._done

    def ping(self) -> str:
        return "ok"


class WorkerGroup:
    def __init__(
        self,
        scaling: ScalingConfig,
        experiment_name: str,
        trial_dir: str,
        execution: str = "inproc",
        restart_count: int = 0,
    ):
        self.scaling = scaling
        self.experiment_name = experiment_name
        self.trial_dir = trial_dir
        self.execution = execution  # "inproc" shares the jax grid; "process"
                                    # isolates ranks (torch process groups)
        import uuid

        # fresh per group (= per fit attempt): scopes rank rendezvous keys
        self.group_token = uuid.uuid4().hex
        self.restart_count = restart_count
        self.workers: List[Any] = []

    def start(self) -> None:
        n = self.scaling.num_workers
        self.workers = [
            TrainWorkerActor.options(
                resources=self.scaling.worker_resources(),
                execution=self.execution,
                max_concurrency=4,
            ).remote(
                rank,
                n,
                self.scaling.num_devices_per_worker,
                self.experiment_name,
                self.trial_dir,
                pin_devices=self.execution != "process",
                group_token=self.group_token,
                restart_count=self.restart_count,
            )
            for rank in range(n)
        ]
        ray_tpu.get([w.ping.remote() for w in self.workers])

    def run_async(self, fn: Callable, config: dict, dataset_shards: List[dict], latest_checkpoint) -> List[Any]:
        return [
            w.run.remote(fn, config, dataset_shards[i] if dataset_shards else {}, latest_checkpoint)
            for i, w in enumerate(self.workers)
        ]

    def dead_workers(self) -> List[Tuple[int, BaseException]]:
        """Ranks the control plane declares DEAD, with the typed error a
        caller should surface.  The liveness guard of the rank-0 drain
        path: a ``kill -9``'d rank whose run future has not resolved yet
        must become a typed :class:`ActorDiedError`, never a hang."""
        from ray_tpu.exceptions import ActorDiedError
        from ray_tpu.runtime.control import ActorState

        cluster = ray_tpu.get_cluster()
        out: List[Tuple[int, BaseException]] = []
        for rank, w in enumerate(self.workers):
            info = cluster.control.actors.get(w._actor_id)
            if info is not None and info.state is ActorState.DEAD:
                out.append(
                    (
                        rank,
                        ActorDiedError(
                            w._actor_id,
                            info.death_cause
                            or f"train worker rank {rank} died mid-run",
                        ),
                    )
                )
        return out

    def poll_all(self) -> Tuple[List[Tuple[int, dict, Any]], bool]:
        """Gather new reports from every rank; done only when all ranks done."""
        reports: List[Tuple[int, dict, Any]] = []
        all_done = True
        for rank, w in enumerate(self.workers):
            worker_reports, done = ray_tpu.get(w.poll.remote())
            for metrics, ckpt in worker_reports:
                reports.append((rank, metrics, ckpt))
            all_done = all_done and done
        return reports, all_done

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
