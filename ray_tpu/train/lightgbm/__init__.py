"""LightGBM data-parallel trainer.

Parity: ``python/ray/train/lightgbm/lightgbm_trainer.py`` (per-worker
``lightgbm.train`` on the worker's shard, train set always in the valid
sets) and ``train/lightgbm/config.py`` (the distributed bootstrap: LightGBM
rendezvous is a ``machines`` host:port list + ``local_listen_port`` +
``num_machines`` params with the data-parallel tree learner — negotiated
here over the cluster KV instead of the reference's backend side channel),
plus ``RayTrainReportCallback`` from ``train/lightgbm/_lightgbm_utils.py``.

Gated on the ``lightgbm`` import; drives only public lightgbm API
(``train``, ``Dataset``, ``Booster``, plain-callable callbacks).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Optional

from ray_tpu.train import session as train_session
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.predictor import Predictor, wrap_predictions_column
from ray_tpu.train.config import TRAIN_DATASET_KEY
from ray_tpu.train.gbdt import (
    eval_shards,
    host_ip,
    kv_rendezvous,
    require_module,
    shard_to_xy,
)
from ray_tpu.util.misc import reserve_port
from ray_tpu.train.trainer import DataParallelTrainer

__all__ = ["LightGBMTrainer", "LightGBMCheckpoint", "RayTrainReportCallback", "LightGBMPredictor"]


class LightGBMCheckpoint(Checkpoint):
    """A checkpoint holding one serialized lightgbm Booster."""

    MODEL_FILENAME = "model.txt"

    @classmethod
    def from_model(cls, booster, base_dir: Optional[str] = None) -> "LightGBMCheckpoint":
        d = base_dir or tempfile.mkdtemp(prefix="lgbm_ckpt_")
        os.makedirs(d, exist_ok=True)
        booster.save_model(os.path.join(d, cls.MODEL_FILENAME))
        return cls(d)

    def get_model(self):
        lightgbm = require_module("lightgbm")
        return lightgbm.Booster(model_file=os.path.join(self.path, self.MODEL_FILENAME))


class RayTrainReportCallback:
    """LightGBM-callback bridge into the train session.

    LightGBM callbacks are plain callables invoked each round with a
    ``CallbackEnv`` namedtuple; this one reports every
    ``(data_name, eval_name)`` pair and checkpoints the booster every
    ``frequency`` rounds plus on the final round (``env.end_iteration``
    marks it — LightGBM has no after-training hook).
    """

    order = 25  # run after lightgbm's own eval-recording callbacks

    def __init__(
        self,
        metrics: Optional[List[str]] = None,
        frequency: int = 0,
        checkpoint_at_end: bool = True,
    ):
        self._metrics = metrics
        self._frequency = frequency
        self._checkpoint_at_end = checkpoint_at_end

    def __call__(self, env) -> None:
        it = env.iteration + 1
        report: Dict[str, Any] = {"training_iteration": it}
        for entry in env.evaluation_result_list or []:
            data_name, eval_name, result = entry[0], entry[1], entry[2]
            key = f"{data_name}-{eval_name}"
            if self._metrics is not None and key not in self._metrics and eval_name not in self._metrics:
                continue
            report[key] = result
        last_round = it >= getattr(env, "end_iteration", it)
        ckpt = None
        if (self._frequency and it % self._frequency == 0) or (
            last_round and self._checkpoint_at_end
        ):
            ckpt = self._maybe_checkpoint(env.model)
        train_session.report(report, checkpoint=ckpt)

    def _maybe_checkpoint(self, model) -> Optional[Checkpoint]:
        ctx = train_session.get_context()
        if ctx.get_world_rank() != 0:
            return None
        return LightGBMCheckpoint.from_model(model)

    @classmethod
    def get_model(cls, checkpoint: Checkpoint):
        """Load the booster out of a checkpoint produced by this callback."""
        return LightGBMCheckpoint(checkpoint.path).get_model()


def _network_params(world: int, rank: int, run_key: str) -> Dict[str, Any]:
    """Negotiate LightGBM's distributed params across the gang.

    Every rank binds a port and publishes ``ip:port`` over the cluster KV;
    the gathered list becomes the ``machines`` param on every rank
    (reference: ``train/lightgbm/config.py`` builds the same list from its
    worker group).  Single-worker gangs return no params.
    """
    if world <= 1:
        return {}
    ip = host_ip()
    # hold the reservation socket OPEN through the rendezvous so the kernel
    # cannot hand a sibling rank on this host the same ephemeral port
    sock = reserve_port()
    port = sock.getsockname()[1]
    try:
        payloads = kv_rendezvous(run_key, rank, world, {"ip": ip, "port": port})
    finally:
        sock.close()  # LightGBM binds it next
    machines = ",".join(f"{p['ip']}:{p['port']}" for p in payloads)
    if len({(p["ip"], p["port"]) for p in payloads}) != world:
        raise RuntimeError(
            f"LightGBM machines negotiation collided: {machines!r} — "
            "two ranks advertised the same endpoint"
        )
    return {
        "machines": machines,
        "local_listen_port": port,
        "num_machines": world,
        "tree_learner": "data",
    }


class LightGBMTrainer(DataParallelTrainer):
    """Distributed LightGBM over the train worker gang.

    Each worker trains on its row shard with the data-parallel tree
    learner; feature histograms allreduce over LightGBM's own socket mesh
    (the ``machines`` list), so every rank ends with the same model.
    """

    def __init__(
        self,
        *,
        params: Optional[Dict[str, Any]] = None,
        label_column: str,
        num_boost_round: int = 10,
        lightgbm_train_kwargs: Optional[Dict[str, Any]] = None,
        report_callback: Optional[RayTrainReportCallback] = None,
        **kwargs,
    ):
        params = dict(params or {})
        train_kwargs = dict(lightgbm_train_kwargs or {})
        dataset_keys = set((kwargs.get("datasets") or {}).keys())
        rc = kwargs.get("run_config")
        run_name = (rc.name if rc is not None and rc.name else None) or f"lgbm_{os.getpid()}"

        def _train_fn(config: dict):
            lightgbm = require_module("lightgbm")
            merged = dict(params)
            merged.update(config or {})
            ctx = train_session.get_context()
            world, rank = ctx.get_world_size(), ctx.get_world_rank()

            ckpt = train_session.get_checkpoint()
            init_model = None
            remaining = num_boost_round
            if ckpt is not None:
                init_model = LightGBMCheckpoint(ckpt.path).get_model()
                done = (
                    int(init_model.current_iteration())
                    if hasattr(init_model, "current_iteration")
                    else 0
                )
                remaining = max(num_boost_round - done, 0)
            if remaining == 0:
                # Already at (or past) the target round count.  LightGBM
                # would run zero iterations and the per-iteration callback
                # would never fire, so re-report the restored model
                # explicitly — otherwise fit() returns no metrics and no
                # checkpoint and the trained model is lost to the caller.
                out_ckpt = (
                    LightGBMCheckpoint.from_model(init_model) if rank == 0 else None
                )
                train_session.report(
                    {"training_iteration": num_boost_round}, checkpoint=out_ckpt
                )
                return

            train_X, train_y = shard_to_xy(
                train_session.get_dataset_shard(TRAIN_DATASET_KEY), label_column
            )
            dtrain = lightgbm.Dataset(train_X, label=train_y)
            valid_sets, valid_names = [dtrain], [TRAIN_DATASET_KEY]
            for name, X, y in eval_shards(dataset_keys, label_column, TRAIN_DATASET_KEY):
                valid_sets.append(lightgbm.Dataset(X, label=y, reference=dtrain))
                valid_names.append(name)

            cb = report_callback or RayTrainReportCallback()
            callbacks = list(train_kwargs.get("callbacks", []))
            callbacks.append(cb)
            extra = {k: v for k, v in train_kwargs.items() if k != "callbacks"}
            # negotiate the socket mesh LAST — data loading above can take
            # minutes, and the advertised port is only reserved, not bound,
            # until lightgbm.train below actually listens on it
            merged.update(
                _network_params(
                    world, rank, f"lgbm_machines/{run_name}/{ctx.get_group_token()}"
                )
            )
            lightgbm.train(
                merged,
                dtrain,
                num_boost_round=remaining,
                valid_sets=valid_sets,
                valid_names=valid_names,
                init_model=init_model,
                callbacks=callbacks,
                **extra,
            )

        super().__init__(_train_fn, train_loop_config={}, **kwargs)


class LightGBMPredictor(Predictor):
    """Batch inference with a trained booster (parity:
    ``train/lightgbm/lightgbm_predictor.py``)."""

    def __init__(self, model, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, preprocessor=None) -> "LightGBMPredictor":
        return cls(LightGBMCheckpoint(checkpoint.path).get_model(), preprocessor)

    def _predict_pandas(self, df, **kwargs):
        import pandas as pd

        preds = self.model.predict(df, **kwargs)
        return pd.DataFrame({"predictions": wrap_predictions_column(preds)})
