"""Predictors: load a trained model from a checkpoint and predict batches.

Parity: ``python/ray/train/predictor.py:40`` (abstract ``Predictor`` with
``from_checkpoint`` / ``from_pandas_udf`` / preprocessor plumbing /
format-dispatching ``predict``, and the deliberate non-serializability that
pushes batch inference through ``Dataset.map_batches`` with a callable
class) — plus a TPU-first ``JaxPredictor`` standing where the reference has
``TorchPredictor`` (``train/torch/torch_predictor.py``): a jitted apply_fn
over numpy batches, params restored from a pytree checkpoint.

Framework predictors for the GBDT trainers live next to their trainers
(``ray_tpu.train.xgboost.XGBoostPredictor``, ``.lightgbm
.LightGBMPredictor``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint

__all__ = ["Predictor", "JaxPredictor", "PredictorNotSerializableException"]


def wrap_predictions_column(arr) -> "Any":
    """A model-output array as one DataFrame column: 1-D stays a column,
    N-D becomes a column of row-arrays (pandas rejects 2-D column values)."""
    arr = np.asarray(arr)
    return arr if arr.ndim == 1 else list(arr)


class PredictorNotSerializableException(RuntimeError):
    """Predictors are driver-side objects; ship the checkpoint to tasks and
    ``from_checkpoint`` there (reference: predictor.py:33)."""


class Predictor:
    """Base predictor (parity: predictor.py:40).

    Subclasses implement ``_predict_pandas`` or ``_predict_numpy``;
    ``predict`` dispatches on the input batch type (DataFrame, dict of
    arrays, or bare ndarray) and applies the fitted preprocessor first.
    """

    def __init__(self, preprocessor: Optional[Any] = None):
        self._preprocessor = preprocessor

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    @classmethod
    def from_pandas_udf(cls, pandas_udf: Callable) -> "Predictor":
        """Wrap a ``df -> df`` function as a Predictor (parity:
        predictor.py:99)."""

        class PandasUDFPredictor(Predictor):
            @classmethod
            def from_checkpoint(cls, checkpoint, **kwargs):
                return cls()

            def _predict_pandas(self, df, **kwargs):
                return pandas_udf(df, **kwargs)

        return PandasUDFPredictor()

    def get_preprocessor(self) -> Optional[Any]:
        return self._preprocessor

    def set_preprocessor(self, preprocessor: Optional[Any]) -> None:
        self._preprocessor = preprocessor

    # ------------------------------------------------------------- predict
    def predict(self, data: Any, **kwargs) -> Any:
        """Predict one batch: DataFrame in → DataFrame out; dict/ndarray in
        → dict/ndarray out."""
        import pandas as pd

        if self._preprocessor is not None:
            data = self._preprocessor.transform_batch(data)
        if isinstance(data, pd.DataFrame):
            return self._predict_pandas(data, **kwargs)
        if isinstance(data, dict):
            out = self._predict_numpy(data, **kwargs)
            return out
        if isinstance(data, np.ndarray):
            return self._predict_numpy(data, **kwargs)
        raise TypeError(
            f"Unsupported batch type {type(data).__name__}; expected "
            "pandas.DataFrame, dict of ndarrays, or ndarray"
        )

    def _require_impl(self, have: str) -> None:
        # the two base hooks cross-convert through each other; a subclass
        # overriding neither must get NotImplementedError, not RecursionError
        other = "_predict_numpy" if have == "_predict_pandas" else "_predict_pandas"
        if getattr(type(self), other) is getattr(Predictor, other):
            raise NotImplementedError(
                f"{type(self).__name__} implements neither _predict_pandas "
                "nor _predict_numpy"
            )

    # subclasses implement at least one of these; the base cross-converts
    def _predict_pandas(self, df, **kwargs):
        import pandas as pd

        self._require_impl("_predict_pandas")
        arrays = {c: df[c].to_numpy() for c in df.columns}
        out = self._predict_numpy(arrays, **kwargs)
        if isinstance(out, dict):
            return pd.DataFrame({k: wrap_predictions_column(v) for k, v in out.items()})
        return pd.DataFrame({"predictions": wrap_predictions_column(out)})

    def _predict_numpy(self, data, **kwargs):
        import pandas as pd

        self._require_impl("_predict_numpy")
        if isinstance(data, dict):
            df = pd.DataFrame({k: list(v) for k, v in data.items()})
        else:
            df = pd.DataFrame({"__value__": list(data)})
        out = self._predict_pandas(df, **kwargs)
        return {c: out[c].to_numpy() for c in out.columns}

    def __reduce__(self):
        raise PredictorNotSerializableException(
            f"{type(self).__name__} is not serializable — pass the Checkpoint "
            "to your tasks/actors and call from_checkpoint() there (this is "
            "what Dataset.map_batches with a callable class does)."
        )


class JaxPredictor(Predictor):
    """Predict with a jitted jax apply function (the TPU stand-in for the
    reference's TorchPredictor).

    ``apply_fn(params, batch_array) -> array``; params come from a pytree
    checkpoint (``Checkpoint.from_pytree``/``to_pytree``).  Inputs are
    stacked feature columns (dict batches) or a raw ndarray.
    """

    def __init__(self, apply_fn: Callable, params: Any, preprocessor=None, jit: bool = True):
        super().__init__(preprocessor)
        import jax

        self.params = params
        self.apply_fn = jax.jit(apply_fn) if jit else apply_fn

    @classmethod
    def from_checkpoint(
        cls, checkpoint: Checkpoint, apply_fn: Callable, preprocessor=None, **kwargs
    ) -> "JaxPredictor":
        return cls(apply_fn, checkpoint.to_pytree(), preprocessor=preprocessor, **kwargs)

    def _predict_numpy(self, data, **kwargs):
        if isinstance(data, dict):
            x = np.stack([np.asarray(v) for v in data.values()], axis=-1)
        else:
            x = np.asarray(data)
        out = np.asarray(self.apply_fn(self.params, x))
        return {"predictions": out}
