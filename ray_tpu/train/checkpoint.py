"""Directory-based checkpoints.

Parity: ``python/ray/train/_checkpoint.py`` (``Checkpoint`` — a handle to a
directory of files; ``from_directory``/``to_directory``/``as_directory``,
metrics attached by the session).

TPU-first delta: first-class helpers for jax pytrees — ``from_pytree`` /
``to_pytree`` serialize a params pytree via orbax when available, falling
back to a pickled host copy (``jax.device_get``) otherwise.
"""

from __future__ import annotations

import contextlib
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # ----------------------------------------------------------- directory
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None or os.path.abspath(path) == self.path:
            return self.path
        os.makedirs(path, exist_ok=True)
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    # --------------------------------------------------------------- dicts
    @classmethod
    def from_dict(cls, data: Dict[str, Any], base_dir: Optional[str] = None) -> "Checkpoint":
        path = os.path.join(base_dir or tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:12]}")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "data.pkl"), "wb") as f:
            pickle.dump(data, f, protocol=5)
        return cls(path)

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    # ------------------------------------------------------------- pytrees
    @classmethod
    def from_pytree(cls, tree: Any, base_dir: Optional[str] = None) -> "Checkpoint":
        """Save a jax pytree (params/opt state).  Orbax when importable —
        the TPU-native checkpoint format with async device→host streaming —
        else pickled ``jax.device_get`` host copies."""
        path = os.path.join(base_dir or tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:12]}")
        os.makedirs(path, exist_ok=True)
        try:
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.join(path, "pytree"), tree)
        except Exception:
            import jax

            with open(os.path.join(path, "pytree.pkl"), "wb") as f:
                pickle.dump(jax.device_get(tree), f, protocol=5)
        return cls(path)

    def to_pytree(self) -> Any:
        orbax_path = os.path.join(self.path, "pytree")
        if os.path.isdir(orbax_path):
            import orbax.checkpoint as ocp

            return ocp.PyTreeCheckpointer().restore(orbax_path)
        with open(os.path.join(self.path, "pytree.pkl"), "rb") as f:
            return pickle.load(f)

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path})"
