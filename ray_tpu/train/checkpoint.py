"""Directory-based checkpoints.

Parity: ``python/ray/train/_checkpoint.py`` (``Checkpoint`` — a handle to a
directory of files; ``from_directory``/``to_directory``/``as_directory``,
metrics attached by the session).

TPU-first delta: first-class helpers for jax pytrees — ``from_pytree`` /
``to_pytree`` serialize a params pytree via orbax when available, falling
back to a pickled host copy (``jax.device_get``) otherwise.

Durability: every pickled artifact is written with the crash-atomic framing
the control-plane snapshots use (``runtime/control.py save_snapshot``):
``magic + blake2b-16(payload) + payload`` into a temp file, fsync, atomic
rename, with the previous generation rotated to ``<path>.prev`` first.  A
writer killed at ANY instant (kill -9 chaos mid-checkpoint) leaves either
the new complete file or the previous complete one; restore rejects torn
files on the digest and falls back to ``.prev``.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional

#: framing shared by every pickled checkpoint artifact.  Distinct magic from
#: the control snapshot (RTSNAP1) so a mis-pointed restore fails loudly.
_CKPT_MAGIC = b"RTCKPT1\n"


def save_framed(path: str, obj: Any) -> None:
    """Crash-atomic pickled write: digest framing + tmp + fsync + rename,
    rotating the previous generation to ``<path>.prev``."""
    payload = pickle.dumps(obj, protocol=5)
    digest = hashlib.blake2b(payload, digest_size=16).digest()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_CKPT_MAGIC + digest + payload)
        f.flush()
        os.fsync(f.fileno())  # bytes durable BEFORE the rename publishes them
    if os.path.exists(path):
        # keep the last good generation: a crash between the two renames
        # still leaves .prev for load_framed's fallback
        os.replace(path, path + ".prev")
    os.replace(tmp, path)  # atomic: readers never see a torn file
    try:
        dir_fd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)  # the renames themselves survive power loss
        finally:
            os.close(dir_fd)
    except OSError:
        pass


def _load_framed_file(path: str) -> Optional[Any]:
    """One framed file -> object, or None if missing/torn.  The digest check
    rejects truncated and bit-flipped files before pickle ever sees them;
    headerless files fall back to plain pickle (pre-framing artifacts)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            raw = f.read()
        if raw.startswith(_CKPT_MAGIC):
            off = len(_CKPT_MAGIC)
            digest, payload = raw[off:off + 16], raw[off + 16:]
            if hashlib.blake2b(payload, digest_size=16).digest() != digest:
                raise ValueError("checkpoint digest mismatch (torn/partial write)")
            return pickle.loads(payload)
        return pickle.loads(raw)
    except Exception:  # noqa: BLE001 — a torn file must fall back, not raise
        return None


def load_framed(path: str) -> Optional[Any]:
    """Framed file -> object; a rejected current generation restores the
    ``.prev`` one rotated by :func:`save_framed`.  None when neither loads."""
    obj = _load_framed_file(path)
    if obj is None:
        obj = _load_framed_file(path + ".prev")
    return obj


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # ----------------------------------------------------------- directory
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None or os.path.abspath(path) == self.path:
            return self.path
        os.makedirs(path, exist_ok=True)
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    # --------------------------------------------------------------- dicts
    @classmethod
    def from_dict(cls, data: Dict[str, Any], base_dir: Optional[str] = None) -> "Checkpoint":
        path = os.path.join(base_dir or tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:12]}")
        os.makedirs(path, exist_ok=True)
        save_framed(os.path.join(path, "data.pkl"), data)
        return cls(path)

    def to_dict(self) -> Dict[str, Any]:
        data = load_framed(os.path.join(self.path, "data.pkl"))
        if data is None:
            raise FileNotFoundError(
                f"no readable checkpoint data at {self.path} (missing or torn)"
            )
        return data

    # ------------------------------------------------------------- pytrees
    @classmethod
    def from_pytree(cls, tree: Any, base_dir: Optional[str] = None) -> "Checkpoint":
        """Save a jax pytree (params/opt state).  Orbax when importable —
        the TPU-native checkpoint format with async device→host streaming —
        else pickled ``jax.device_get`` host copies."""
        path = os.path.join(base_dir or tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:12]}")
        os.makedirs(path, exist_ok=True)
        try:
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.join(path, "pytree"), tree)
        except Exception:
            import jax

            save_framed(os.path.join(path, "pytree.pkl"), jax.device_get(tree))
        return cls(path)

    def to_pytree(self) -> Any:
        orbax_path = os.path.join(self.path, "pytree")
        if os.path.isdir(orbax_path):
            import orbax.checkpoint as ocp

            return ocp.PyTreeCheckpointer().restore(orbax_path)
        tree = load_framed(os.path.join(self.path, "pytree.pkl"))
        if tree is None:
            raise FileNotFoundError(
                f"no readable pytree checkpoint at {self.path} (missing or torn)"
            )
        return tree

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path})"
