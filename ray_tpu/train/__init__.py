"""ray_tpu.train: distributed training orchestration.

TPU-native rebuild of the reference's Ray Train (``python/ray/train/``,
SURVEY §2.4/§3.5): trainers spawn a gang of device-pinned in-process worker
actors, ScalingConfig maps to a jax device mesh, ``train.report`` streams
metrics/checkpoints to the driver, and checkpoints are pytree directories.
"""

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.controller import TrainController, global_batch
from ray_tpu.train.config import (
    TRAIN_DATASET_KEY,
    BackendConfig,
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
    SyncConfig,
)
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.predictor import (
    JaxPredictor,
    Predictor,
    PredictorNotSerializableException,
)
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataConfig,
    DataParallelTrainer,
    JaxTrainer,
    Result,
    TorchTrainer,
    TrainingIterator,
)

__all__ = [
    "TRAIN_DATASET_KEY",
    "BackendConfig",
    "BaseTrainer",
    "SyncConfig",
    "TrainingIterator",
    "Checkpoint",
    "CheckpointConfig",
    "DataConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxPredictor",
    "JaxTrainer",
    "Predictor",
    "PredictorNotSerializableException",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TorchTrainer",
    "TrainContext",
    "TrainController",
    "global_batch",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
]
