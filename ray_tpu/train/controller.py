"""TrainController: elastic gang-scheduled SPMD data-parallel training.

The composition layer ROADMAP item 5 asked for: the pieces all existed —
``StageGroup`` gangs with typed BROKEN + ``repair()`` (dag/plan.py), the
drain path and crash-atomic snapshots (runtime/cluster.py, runtime/control.py),
admission arbitration (runtime/admission.py), the streaming Dataset executor
(data/executor.py) — and this controller wires them into one fault-tolerant
training job:

* the training stage is a **StageGroup gang** compiled into an
  ``ExecutionPlan``: one jit'd member step traced per mesh size (the warmup
  primes each per-member shard shape exactly once), every optimizer step is
  one gang dispatch that splits the global batch across members and
  reassembles the packed ``[loss_sum, count, grad]`` rows;
* **bit-exact state**: params/momentum/step/RNG live on the controller, the
  member steps are stateless, and the update sums member rows in fixed
  member order inside one jit'd reduce — restoring a checkpoint and
  replaying the same (seed, step) batches reproduces the loss curve
  byte-for-byte (chaos invariant 12 audits exactly this);
* **repair-and-resume**: a gang-member death mid-step flips the plan BROKEN
  with the typed error; ``recover()`` restores the latest digest-framed
  checkpoint (train/checkpoint.py ``save_framed``), re-runs ``repair()``,
  and falls back to a shrink-rebuild when a member is permanently gone;
* **elastic resize**: ``resize()`` grows/shrinks the gang with zero lost
  step state (checkpoint first), re-tracing only at never-seen mesh sizes;
  scale-down drains a departing member's now-empty node through
  ``Cluster.drain_node`` (``node_drains_total{outcome=ok}``);
* **train-while-serve**: with ``train_preemptible`` the gang registers as a
  background admission source and ``preempt_member()`` implements the
  preemption contract (checkpoint -> shrink -> continue).

Batch determinism: ``global_batch(seed, step, ...)`` is a pure function —
world size changes WHERE the shard boundaries fall, never which rows are
drawn or their order, so an elastic resize continues the same data stream.
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

_DTYPE = "float32"


def global_batch(
    seed: int,
    step: int,
    *,
    batch_size: int,
    feature_dim: int = 0,
    rows: Optional[np.ndarray] = None,
) -> np.ndarray:
    """The global batch for one optimizer step — a pure function of
    (seed, step).  With ``rows`` (a materialized ``[N, F]`` feature matrix,
    e.g. from the streaming Dataset executor) the batch draws row indices
    from the seeded stream; without it the features themselves are drawn.
    World size is deliberately NOT an input: resizing the gang re-shards
    the same batches, it never changes the data order."""
    rng = np.random.default_rng([int(seed), int(step)])
    if rows is not None:
        idx = rng.integers(0, rows.shape[0], size=batch_size)
        return np.ascontiguousarray(rows[idx], dtype=np.float32)
    return rng.standard_normal((batch_size, feature_dim), dtype=np.float32)


def _default_loss(params, batch):
    """Least-squares probe: predict each row's feature sum from a linear
    head.  Returns the SUM (not mean) of per-row losses so member-shard
    sums add to the global sum regardless of how the batch is sharded."""
    import jax.numpy as jnp

    w = params[:-1]
    b = params[-1]
    pred = batch @ w + b
    target = jnp.sum(batch, axis=1)
    return jnp.sum((pred - target) ** 2)


class TrainController:
    """Drives one elastic gang-scheduled training job over a compiled plan.

    The recovery ladder (``recover()``):

    1. restore optimizer/step/RNG state from the latest digest-framed step
       checkpoint (torn files fall back to ``.prev``), truncating the loss
       history to the checkpoint step;
    2. ``plan.repair()`` — a restartable member comes back through the
       restart FSM and the SAME gang resumes (``train_repairs_total
       {outcome=repaired}``);
    3. a permanently-dead member (kill -9 past its restart budget, or a
       preemption) fails repair fast — the gang rebuilds at the largest
       legal size from fresh members (``outcome=shrunk``), bounded below by
       ``train_gang_min_members``; below the floor the typed error
       surfaces (``outcome=failed``).

    Every recovery appends an audit row to ``cluster.train_repair_audits``
    (restored state + accumulating post-repair losses + a bound replay
    callable); chaos invariant 12 replays each audit from its checkpoint
    and byte-compares the trajectories.
    """

    def __init__(
        self,
        name: str,
        *,
        world_size: int = 2,
        batch_size: int = 32,
        feature_dim: int = 8,
        seed: int = 0,
        learning_rate: float = 0.05,
        momentum: float = 0.9,
        loss_fn: Optional[Callable[[Any, Any], Any]] = None,
        dataset: Any = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_period: Optional[int] = None,
        min_members: Optional[int] = None,
        preemptible: Optional[bool] = None,
        repair_timeout: float = 30.0,
        member_resources: Optional[List[dict]] = None,
    ):
        import jax

        import ray_tpu
        from ray_tpu.core.config import get_config

        cfg = get_config()
        if batch_size % world_size != 0:
            raise ValueError(
                f"batch_size {batch_size} must divide evenly across the "
                f"gang ({world_size} members)"
            )
        self.name = name
        self._batch_size = batch_size
        self._feature_dim = feature_dim
        self._seed = seed
        self._learning_rate = learning_rate
        self._momentum = momentum
        self._loss_fn = loss_fn or _default_loss
        self._repair_timeout = repair_timeout
        self._member_resources = list(member_resources or [])
        self._checkpoint_period = (
            checkpoint_period
            if checkpoint_period is not None
            else cfg.train_checkpoint_period_steps
        )
        self._min_members = (
            min_members if min_members is not None else cfg.train_gang_min_members
        )
        self.preemptible = (
            preemptible if preemptible is not None else cfg.train_preemptible
        )
        self._checkpoint_dir = checkpoint_dir or tempfile.mkdtemp(
            prefix=f"rt_train_{name}_"
        )
        os.makedirs(self._checkpoint_dir, exist_ok=True)

        self._rows: Optional[np.ndarray] = None
        if dataset is not None:
            from ray_tpu.data.executor import bundles_to_feature_rows

            self._rows = bundles_to_feature_rows(dataset._execute(preserve_order=True))
            self._feature_dim = int(self._rows.shape[1])
        self._nparams = self._feature_dim + 1

        # deterministic initial state — replayable from (seed) alone
        init_rng = np.random.default_rng([int(seed), 0xC0FFEE])
        self._params = init_rng.standard_normal(self._nparams, dtype=np.float32)
        self._mom = np.zeros(self._nparams, dtype=np.float32)
        self._rng_key = np.asarray(jax.random.PRNGKey(seed))
        self._step = 0
        self._loss_history: List[float] = []

        # ONE jit per controller: traced once per per-member shard shape —
        # the warmup primes each mesh size exactly once, revisited sizes
        # hit the trace cache (tests assert _cache_size() stays flat)
        self.step_fn = jax.jit(self._member_step)
        self._update_fn = jax.jit(self._update)

        self._lock = threading.RLock()
        self._members: List[Any] = []
        self._plan = None
        self._last_checkpoint: Optional[str] = None
        self.resize_history: List[dict] = []
        self.repair_history: List[dict] = []
        self._open_audits: List[dict] = []

        self._cluster = ray_tpu.get_cluster()
        self._admission_token: Optional[int] = None
        if self.preemptible:
            from ray_tpu.runtime import admission

            self._admission_token = admission.register_admission_source(
                f"train:{name}", self._admission_snapshot
            )
        self._cluster.train_controllers[name] = self
        self._build_gang(world_size)

    # ------------------------------------------------------------------
    # jit'd math — everything that must be bit-exact lives here
    # ------------------------------------------------------------------
    def _member_step(self, params2d, batch):
        """Stateless per-member step: unpack the replicated ``[1, P]``
        params row, take value-and-grad of the loss SUM over this member's
        batch shard, and pack ``[loss_sum, row_count, grad]`` into one
        ``[1, P+2]`` row the gang assembly concatenates."""
        import jax
        import jax.numpy as jnp

        params = params2d[0]
        loss, grad = jax.value_and_grad(self._loss_fn)(params, batch)
        row = jnp.concatenate(
            [
                jnp.reshape(loss, (1,)),
                jnp.full((1,), batch.shape[0], dtype=params.dtype),
                grad,
            ]
        )
        return row[None, :]

    def _update(self, params, mom, rows):
        """One optimizer step from the assembled member rows.  The member
        sum is an explicit sequential reduce in member order — the float
        addition order is pinned by construction, so the same checkpoint
        plus the same batches reproduces the same bits."""
        total = rows[0]
        for i in range(1, rows.shape[0]):
            total = total + rows[i]
        loss_sum, count = total[0], total[1]
        grad = total[2:] / count
        mom_new = self._momentum * mom + grad
        params_new = params - self._learning_rate * mom_new
        return params_new, mom_new, loss_sum / count

    # ------------------------------------------------------------------
    # gang lifecycle
    # ------------------------------------------------------------------
    def _legal_size(self, n: int) -> int:
        """Largest gang size <= n that divides the batch and respects the
        member floor; 0 when none exists."""
        for k in range(min(n, self._batch_size), 0, -1):
            if self._batch_size % k == 0 and k >= self._min_members:
                return k
        return 0

    # rt-lint: guarded-by(_lock) -- callers: _resize_locked/_recover_locked
    # hold it; __init__ runs pre-publication with exclusive access (stronger)
    def _build_gang(self, world_size: int, members: Optional[List[Any]] = None) -> None:
        import ray_tpu
        from ray_tpu.dag import InputNode, StageGroup

        step_fn = self.step_fn

        @ray_tpu.remote
        class _GangMember:
            def step(self, params2d, batch):
                return step_fn(params2d, batch)

        members = list(members or [])
        while len(members) > world_size:
            ray_tpu.kill(members.pop(), no_restart=True)
        for i in range(len(members), world_size):
            opts: Dict[str, Any] = dict(execution="inproc", max_restarts=1)
            if self._member_resources:
                opts["resources"] = self._member_resources[
                    i % len(self._member_resources)
                ]
                opts["num_cpus"] = 0
            members.append(_GangMember.options(**opts).remote())
        self._members = members
        gang = StageGroup(
            members,
            "step",
            split_axis=0,
            warmup=[
                ((1, self._nparams), _DTYPE),
                ((self._batch_size, self._feature_dim), _DTYPE),
            ],
        )
        with InputNode() as inp:
            out = gang.bind(inp[0], inp[1])
        self._plan = out.compile_plan(name=f"train:{self.name}")

    # rt-lint: guarded-by(_lock) -- callers: _resize_locked/_recover_locked/
    # shutdown hold it
    def _teardown_plan(self) -> None:
        if self._plan is not None:
            try:
                self._plan.teardown()
            except Exception:  # noqa: BLE001 — a broken plan tears down best-effort
                pass
            self._plan = None

    def _member_node(self, member) -> Optional[Any]:
        info = self._cluster.control.actors.get(member._actor_id)
        return info.node_id if info is not None else None

    # rt-lint: guarded-by(_lock) -- caller: _recover_locked holds it
    def _alive_members(self) -> List[Any]:
        from ray_tpu.runtime.control import ActorState

        alive = []
        for m in self._members:
            info = self._cluster.control.actors.get(m._actor_id)
            if info is not None and info.state is not ActorState.DEAD:
                alive.append(m)
        return alive

    # ------------------------------------------------------------------
    # the train loop
    # ------------------------------------------------------------------
    @property
    # rt-lint: disable=lock-discipline -- observability snapshot: a torn
    # read only skews a status line, never a training step
    def world_size(self) -> int:
        return len(self._members)

    @property
    # rt-lint: disable=lock-discipline -- observability snapshot: a torn
    # read only skews a status line, never a training step
    def step_count(self) -> int:
        return self._step

    # rt-lint: disable=lock-discipline -- observability snapshot: the list
    # copy tolerates a step landing concurrently
    def losses(self) -> List[float]:
        return list(self._loss_history)

    def step(self) -> float:
        """One optimizer step: one gang dispatch + one jit'd update."""
        with self._lock:
            return self._step_locked()

    def _step_locked(self) -> float:
        import jax
        import jax.numpy as jnp

        from ray_tpu.observability import metric_defs

        batch = global_batch(
            self._seed,
            self._step,
            batch_size=self._batch_size,
            feature_dim=self._feature_dim,
            rows=self._rows,
        )
        params2d = jnp.asarray(self._params)[None, :]
        rows = self._plan.execute(params2d, jnp.asarray(batch))
        p, m, loss = self._update_fn(
            jnp.asarray(self._params), jnp.asarray(self._mom), rows
        )
        self._params = np.asarray(jax.device_get(p))
        self._mom = np.asarray(jax.device_get(m))
        # advance the RNG state so it is genuinely stateful (and therefore
        # genuinely restored): derive the next key from the current one
        self._rng_key = np.asarray(
            jax.random.fold_in(jnp.asarray(self._rng_key), self._step)
        )
        loss_val = float(np.float32(jax.device_get(loss)))
        self._loss_history.append(loss_val)
        self._step += 1
        metric_defs.TRAIN_STEPS.inc()
        for audit in self._open_audits:
            audit["losses"].append(loss_val)
        if self._checkpoint_period and self._step % self._checkpoint_period == 0:
            self.save_checkpoint()
        return loss_val

    # rt-lint: disable=lock-discipline -- the loop bound reads _step
    # optimistically; every mutation happens inside step()/recover(),
    # which take the lock, so a torn read costs at most one extra
    # loop-condition check
    def run(self, num_steps: int, *, auto_repair: bool = True) -> List[float]:
        """Run ``num_steps`` steps with the recovery ladder armed: a typed
        gang failure mid-step triggers ``recover()`` and the loop resumes
        from the restored step (re-running steps lost since the last
        checkpoint).  ``auto_repair=False`` surfaces the typed error."""
        from ray_tpu.exceptions import RayActorError, WorkerCrashedError

        target = self._step + num_steps
        while self._step < target:
            try:
                self.step()
            except (RayActorError, WorkerCrashedError) as exc:
                if not auto_repair:
                    raise
                self.recover(error=exc)
        return self.losses()

    # ------------------------------------------------------------------
    # checkpoint / restore — crash-atomic digest framing
    # ------------------------------------------------------------------
    def _state(self) -> Dict[str, Any]:
        with self._lock:  # RLock: safe from locked and unlocked callers
            return {
                "name": self.name,
                "step": self._step,
                "seed": self._seed,
                "params": np.asarray(self._params, dtype=np.float32),
                "momentum": np.asarray(self._mom, dtype=np.float32),
                "rng_key": np.asarray(self._rng_key),
                "world_size": len(self._members),
                "loss_history": np.asarray(self._loss_history, dtype=np.float32),
            }

    def _apply_state(self, state: Dict[str, Any]) -> None:
        with self._lock:  # RLock: safe from locked and unlocked callers
            self._params = np.asarray(state["params"], dtype=np.float32).copy()
            self._mom = np.asarray(state["momentum"], dtype=np.float32).copy()
            self._rng_key = np.asarray(state["rng_key"]).copy()
            self._step = int(state["step"])
            self._loss_history = [
                float(x)
                for x in np.asarray(state["loss_history"], dtype=np.float32)
            ]

    @property
    def checkpoint_path(self) -> str:
        return os.path.join(self._checkpoint_dir, "state.ckpt")

    @property
    def last_checkpoint(self) -> Optional[str]:
        return self._last_checkpoint

    def save_checkpoint(self) -> str:
        """Write the step state with the crash-atomic framing (tmp + fsync
        + rename + ``.prev`` rotation) and mirror a summary into the
        durable control KV so the job rides ``restart_head``."""
        from ray_tpu.observability import metric_defs
        from ray_tpu.train.checkpoint import save_framed

        t0 = time.perf_counter()
        path = self.checkpoint_path
        with self._lock:  # RLock: safe from locked and unlocked callers
            state = self._state()
            summary = {
                "name": self.name,
                "step": self._step,
                "checkpoint": path,
                "world_size": len(self._members),
                "seed": self._seed,
                "batch_size": self._batch_size,
                "feature_dim": self._feature_dim,
            }
        save_framed(path, state)
        self._last_checkpoint = path
        metric_defs.TRAIN_CHECKPOINT_SECONDS.observe(time.perf_counter() - t0)
        try:
            # head failover must not orphan the job: the claim summary
            # rides the control snapshot (restore_snapshot -> kv.restore)
            self._cluster.control.kv.put(
                f"train/{self.name}".encode(),
                pickle.dumps(summary, protocol=5),
            )
        except Exception:  # noqa: BLE001 — KV mirroring is best-effort
            logger.exception("train %s: control-KV checkpoint mirror failed", self.name)
        return path

    def restore(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Load the latest digest-valid checkpoint (falling back to
        ``.prev`` on a torn file) and install its state."""
        from ray_tpu.train.checkpoint import load_framed

        state = load_framed(path or self.checkpoint_path)
        if state is None:
            raise FileNotFoundError(
                f"no readable train checkpoint at {path or self.checkpoint_path}"
            )
        self._apply_state(state)
        return state

    @classmethod
    def claim(cls, name: str, **overrides) -> "TrainController":
        """Claim an orphaned job after a head failover: the KV summary
        (restored by the head snapshot) names the checkpoint to resume
        from; a fresh controller restores it and continues bit-exactly."""
        import ray_tpu

        cluster = ray_tpu.get_cluster()
        raw = cluster.control.kv.get(f"train/{name}".encode())
        if raw is None:
            raise KeyError(f"no claimable train job {name!r} in the control KV")
        summary = pickle.loads(raw)
        kwargs = dict(
            world_size=summary["world_size"],
            seed=summary["seed"],
            batch_size=summary["batch_size"],
            feature_dim=summary["feature_dim"],
            checkpoint_dir=os.path.dirname(summary["checkpoint"]),
        )
        kwargs.update(overrides)
        ctl = cls(name, **kwargs)
        ctl.restore(summary["checkpoint"])
        return ctl

    # ------------------------------------------------------------------
    # recovery ladder
    # ------------------------------------------------------------------
    def recover(self, error: Optional[BaseException] = None, timeout: Optional[float] = None) -> str:
        with self._lock:
            return self._recover_locked(error, timeout or self._repair_timeout)

    def _recover_locked(self, error, timeout: float) -> str:
        from ray_tpu.observability import metric_defs
        from ray_tpu.train.checkpoint import load_framed

        # 1. restore the latest good checkpoint (or the deterministic
        #    initial state when none was written yet)
        state = load_framed(self.checkpoint_path)
        if state is not None:
            self._apply_state(state)
        else:
            import jax

            rng = np.random.default_rng([int(self._seed), 0xC0FFEE])
            self._params = rng.standard_normal(self._nparams, dtype=np.float32)
            self._mom = np.zeros(self._nparams, dtype=np.float32)
            self._rng_key = np.asarray(jax.random.PRNGKey(self._seed))
            self._step = 0
            self._loss_history = []
            state = self._state()
        resume_step = self._step
        # earlier audits stop accumulating: their recorded prefix up to the
        # restored step is still a valid continuous trajectory
        for audit in self._open_audits:
            keep = max(0, resume_step - audit["start_step"])
            del audit["losses"][keep:]
            audit["open"] = False
        self._open_audits = []

        # 2. repair the SAME gang in place (restartable member death)
        outcome = "repaired"
        try:
            self._plan.repair(timeout=timeout)
        except Exception as repair_exc:  # noqa: BLE001 — ladder rung 3 below
            # 3. permanently-dead member: shrink-rebuild from fresh members
            alive = self._alive_members()
            new_size = self._legal_size(len(alive))
            if new_size <= 0:
                metric_defs.TRAIN_REPAIRS.inc(tags={"outcome": "failed"})
                self.repair_history.append(
                    {"step": resume_step, "outcome": "failed",
                     "error": type(error or repair_exc).__name__}
                )
                raise (error or repair_exc)
            self._teardown_plan()
            for m in self._members:
                try:
                    import ray_tpu

                    ray_tpu.kill(m, no_restart=True)
                except Exception:  # noqa: BLE001 — already-dead members
                    pass
            self._build_gang(new_size)
            outcome = "shrunk"
        metric_defs.TRAIN_REPAIRS.inc(tags={"outcome": outcome})
        self.repair_history.append(
            {
                "step": resume_step,
                "outcome": outcome,
                "world_size": len(self._members),
                "error": type(error).__name__ if error is not None else None,
            }
        )
        # invariant-12 audit: the restored state + the losses that follow
        # must equal an uninterrupted replay from the same state
        audit = {
            "controller": self.name,
            "start_step": resume_step,
            "world_size": len(self._members),
            "outcome": outcome,
            "state": state,
            "losses": [],
            "open": True,
            "replay": self.replay,
        }
        self._open_audits.append(audit)
        self._cluster.train_repair_audits.append(audit)
        return outcome

    def replay(self, state: Dict[str, Any], world_size: int, num_steps: int) -> List[float]:
        """Uninterrupted reference run: from ``state``, compute ``num_steps``
        losses at ``world_size`` WITHOUT the plan — same jit'd member step
        on the same shard shapes in the same member order, so the result is
        bit-identical to what the gang produced (chaos invariant 12)."""
        import jax
        import jax.numpy as jnp

        params = np.asarray(state["params"], dtype=np.float32)
        mom = np.asarray(state["momentum"], dtype=np.float32)
        step0 = int(state["step"])
        losses: List[float] = []
        for s in range(step0, step0 + num_steps):
            batch = global_batch(
                self._seed, s,
                batch_size=self._batch_size,
                feature_dim=self._feature_dim,
                rows=self._rows,
            )
            params2d = jnp.asarray(params)[None, :]
            shards = np.split(batch, world_size, axis=0)
            rows = jnp.concatenate(
                [self.step_fn(params2d, jnp.asarray(sh)) for sh in shards], axis=0
            )
            p, m, loss = self._update_fn(jnp.asarray(params), jnp.asarray(mom), rows)
            params = np.asarray(jax.device_get(p))
            mom = np.asarray(jax.device_get(m))
            losses.append(float(np.float32(jax.device_get(loss))))
        return losses

    # ------------------------------------------------------------------
    # elasticity
    # ------------------------------------------------------------------
    def resize(self, new_size: int, *, reason: str = "scale_up") -> int:
        """Grow/shrink the gang with zero lost step state: checkpoint,
        rebuild the plan at the new size (keeping surviving members),
        drain a departing member's now-empty node.  Returns the new size."""
        with self._lock:
            return self._resize_locked(new_size, reason)

    def _resize_locked(self, new_size: int, reason: str) -> int:
        import ray_tpu
        from ray_tpu.observability import metric_defs

        old_size = len(self._members)
        new_size = self._legal_size(new_size)
        if new_size <= 0:
            raise ValueError(
                f"no legal gang size <= requested for batch {self._batch_size} "
                f"and floor {self._min_members}"
            )
        if new_size == old_size:
            return old_size
        # a resize changes the shard arithmetic, so the loss trajectory is
        # only comparable to a fixed-size replay up to this boundary: seal
        # any open repair audits (their recorded prefix stays valid)
        for audit in self._open_audits:
            audit["open"] = False
        self._open_audits = []
        self.save_checkpoint()  # zero lost step state across the rebuild
        self._teardown_plan()
        if new_size < old_size:
            departing = self._members[new_size:]
            keep = self._members[:new_size]
            keep_nodes = {self._member_node(m) for m in keep}
            head_id = getattr(self._cluster.head_node, "node_id", None)
            for m in departing:
                node_id = self._member_node(m)
                ray_tpu.kill(m, no_restart=True)
                # PR 6 drain path: a departing member's node, once empty of
                # gang members (and not the head), drains gracefully —
                # sole-replica objects evacuate, node_drains_total{outcome=ok}
                if (
                    node_id is not None
                    and node_id not in keep_nodes
                    and node_id != head_id
                ):
                    try:
                        self._cluster.drain_node(node_id)
                    except Exception:  # noqa: BLE001 — drain is best-effort
                        logger.exception(
                            "train %s: drain of departing node failed", self.name
                        )
            self._build_gang(new_size, members=keep)
        else:
            self._build_gang(new_size, members=list(self._members))
        metric_defs.TRAIN_GANG_RESIZES.inc(tags={"reason": reason})
        self.resize_history.append(
            {"step": self._step, "from": old_size, "to": new_size, "reason": reason}
        )
        return new_size

    def elastic_tick(self) -> int:
        """Autoscaler hook: reconcile the gang size against live capacity.
        Capacity = total CPU across alive, non-draining nodes; the gang
        absorbs spare capacity up to the largest legal size and shrinks
        when capacity left."""
        draining = getattr(self._cluster.cluster_scheduler, "is_draining", None)
        cpus = 0.0
        for node_id, node in list(self._cluster.nodes.items()):
            if node.dead:
                continue
            if draining is not None and draining(node_id):
                continue
            cpus += node.pool.total.to_dict().get("CPU", 0.0)
        desired = self._legal_size(max(1, int(cpus)))
        # rt-lint: disable=lock-discipline -- optimistic gate: the resize
        # re-checks plan state under the lock and no-ops on an equal size
        current = len(self._members)
        if desired and desired != current:
            reason = "scale_up" if desired > current else "scale_down"
            with self._lock:
                if self._plan is not None and self._plan.state == "READY":
                    return self._resize_locked(desired, reason)
        return current

    def preempt_member(self, index: Optional[int] = None, *, graceful: bool = True):
        """The preemption contract (train-while-serve): take one member
        away from the gang.  Graceful = checkpoint -> shrink -> continue
        (what a serving burst does through admission); non-graceful =
        hard-kill the member mid-step (chaos `preempt_gang_member`) — the
        next step surfaces the typed error and ``recover()`` shrinks."""
        import ray_tpu

        if not self.preemptible:
            raise RuntimeError(
                f"train job {self.name!r} is not preemptible "
                "(train_preemptible=False)"
            )
        with self._lock:
            n = len(self._members)
            if graceful:
                return self._resize_locked(
                    self._legal_size(n - 1) or n, "preempt"
                )
            victim = self._members[index if index is not None else n - 1]
        ray_tpu.kill(victim, no_restart=True)
        return n

    # rt-lint: disable=lock-discipline -- observability snapshot: torn
    # reads only skew a dashboard poll, never admission decisions
    def _admission_snapshot(self) -> dict:
        return {
            "kind": "train",
            "preemptible": True,
            "gang_size": len(self._members),
            "step": self._step,
        }

    # ------------------------------------------------------------------
    # observability / shutdown
    # ------------------------------------------------------------------
    # rt-lint: disable=lock-discipline -- observability snapshot (GET
    # /api/train, `rt train`): torn reads only skew one poll
    def status(self) -> dict:
        return {
            "name": self.name,
            "gang_size": len(self._members),
            "step": self._step,
            "seed": self._seed,
            "batch_size": self._batch_size,
            "preemptible": self.preemptible,
            "plan_state": self._plan.state if self._plan is not None else None,
            "last_checkpoint": self._last_checkpoint,
            "last_loss": self._loss_history[-1] if self._loss_history else None,
            "resizes": list(self.resize_history),
            "repairs": list(self.repair_history),
        }

    def shutdown(self) -> None:
        import ray_tpu

        with self._lock:
            for audit in self._open_audits:
                audit["open"] = False
            self._open_audits = []
            self._teardown_plan()
            for m in self._members:
                try:
                    ray_tpu.kill(m, no_restart=True)
                except Exception:  # noqa: BLE001
                    pass
            self._members = []
        if self._admission_token is not None:
            from ray_tpu.runtime import admission

            admission.unregister_admission_source(self._admission_token)
            self._admission_token = None
        self._cluster.train_controllers.pop(self.name, None)
