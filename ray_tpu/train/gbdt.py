"""Shared plumbing for the gradient-boosting trainers (XGBoost / LightGBM).

Parity: ``python/ray/train/xgboost/xgboost_trainer.py:74`` and
``python/ray/train/lightgbm/lightgbm_trainer.py`` — both reference trainers
are DataParallelTrainers whose per-worker loop trains the framework's
booster on the worker's dataset shard, reporting eval metrics every boosting
round and checkpointing the model through the train session.  The
distributed rendezvous differs per framework (xgboost: rabit-style tracker;
lightgbm: a ``machines`` host list) — the reference wires both through its
backend config classes (``train/xgboost/config.py``,
``train/lightgbm/config.py``); here both ride the cluster's internal KV
store instead of a side channel.

The frameworks themselves are not bundled with ray_tpu: the trainers work
when ``xgboost`` / ``lightgbm`` import, and raise an actionable error
otherwise (same gating style as the Tune external searchers).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional


def require_module(name: str):
    """Import a GBDT framework or raise an actionable error."""
    try:
        return __import__(name)
    except ImportError as exc:  # pragma: no cover - exercised via stub test
        raise ImportError(
            f"{name} is required for this trainer but is not installed. "
            f"Run `pip install {name}` (any recent version works; the "
            f"trainer only drives the public train()/Booster APIs)."
        ) from exc


def shard_to_xy(shard, label_column: str):
    """Materialize a dataset shard into (features_df, label_series)."""
    df = shard.to_pandas()
    if label_column not in df.columns:
        raise ValueError(
            f"label_column={label_column!r} not in dataset columns {list(df.columns)}"
        )
    return df.drop(columns=[label_column]), df[label_column]


def host_ip() -> str:
    """This host's address as reachable by gang peers on other nodes.

    Routed-UDP-connect lookup (``util.misc.get_node_ip_address``) — NOT
    ``gethostbyname(hostname)``, which resolves to the unroutable 127.0.1.1
    on Debian-family images."""
    from ray_tpu.util.misc import get_node_ip_address

    return get_node_ip_address()


def default_rendezvous_timeout() -> float:
    """Gang-rendezvous deadline (seconds).  Env-overridable because the
    slowest rank may be separated from the fastest by data-load skew."""
    import os

    return float(os.environ.get("RAY_TPU_GBDT_RENDEZVOUS_TIMEOUT_S", "300"))


def kv_rendezvous(
    key_prefix: str,
    rank: int,
    world_size: int,
    payload: Dict[str, Any],
    timeout: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """All-gather small JSON payloads across a training gang via internal KV.

    Every rank publishes ``{key_prefix}/{rank}`` and blocks until all
    ``world_size`` entries exist; returns the payloads in rank order.  Used
    for the GBDT collective bootstraps (tracker address, machines list) the
    reference passes through its backend configs.
    """
    from ray_tpu.experimental import internal_kv

    if timeout is None:
        timeout = default_rendezvous_timeout()

    def _gather(prefix: str, what: str) -> List[bytes]:
        deadline = time.monotonic() + timeout
        while True:
            vals = []
            for r in range(world_size):
                raw = internal_kv._internal_kv_get(f"{prefix}/{r}".encode())
                if raw is None:
                    break
                vals.append(raw)
            if len(vals) == world_size:
                return vals
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"GBDT rendezvous {prefix!r} ({what}): {len(vals)}/"
                    f"{world_size} ranks reported within {timeout}s"
                )
            time.sleep(0.02)

    internal_kv._internal_kv_put(
        f"{key_prefix}/{rank}".encode(), json.dumps(payload).encode()
    )
    out = [json.loads(raw) for raw in _gather(key_prefix, "payloads")]
    # Cleanup must not race slower readers: every rank acks its read, rank 0
    # deletes after all acks.  Best-effort only — a rank that dies before
    # acking must not wedge the survivors, and stale keys are harmless
    # because callers scope key_prefix by the gang's per-attempt token.
    internal_kv._internal_kv_put(f"{key_prefix}/ack/{rank}".encode(), b"1")
    if rank == 0:
        try:
            _gather(f"{key_prefix}/ack", "acks")
        except TimeoutError:
            return out
        for r in range(world_size):
            internal_kv._internal_kv_del(f"{key_prefix}/{r}".encode())
            internal_kv._internal_kv_del(f"{key_prefix}/ack/{r}".encode())
    return out


def eval_shards(dataset_keys, label_column: str, train_key: str):
    """Yield ``(name, X, y)`` for every non-train dataset shard of the
    session, in sorted order — the shared eval-set loop of both trainers."""
    from ray_tpu.train import session as train_session

    for name in sorted(dataset_keys):
        if name == train_key:
            continue
        X, y = shard_to_xy(train_session.get_dataset_shard(name), label_column)
        yield name, X, y
