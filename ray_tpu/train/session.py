"""Per-worker training session: context, report channel, dataset shards.

Parity: ``python/ray/train/_internal/session.py`` — ``train.report(metrics,
checkpoint)`` streams results from workers to the driver;
``train.get_context()`` exposes rank/world-size/etc.;
``train.get_dataset_shard(name)`` hands each worker its Data shard
(``_internal/data_config.py``).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_session_local = threading.local()


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    experiment_name: str = "train"
    trial_dir: str = "/tmp"
    devices: List[Any] = field(default_factory=list)
    mesh: Any = None
    # unique per worker-gang attempt; scopes cross-rank rendezvous keys so
    # retries / concurrent same-name runs can never read each other's state
    group_token: str = ""
    # how many times the gang has been rebuilt after a failure (0 on the
    # first attempt): repair-and-resume loops use this to distinguish a
    # fresh run from a restart resuming off train.get_checkpoint()
    restart_count: int = 0

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_devices(self) -> List[Any]:
        """The jax devices assigned to this worker (its mesh slice)."""
        return self.devices

    def get_mesh(self):
        """This worker's ``jax.sharding.Mesh`` over its assigned devices."""
        return self.mesh

    def get_group_token(self) -> str:
        """Opaque id shared by all ranks of one gang attempt."""
        return self.group_token

    def get_restart_count(self) -> int:
        """0 on the first gang attempt, incremented per repair restart."""
        return self.restart_count


class _Session:
    def __init__(
        self,
        context: TrainContext,
        reporter,
        dataset_shards: Optional[Dict[str, Any]] = None,
        latest_checkpoint=None,
    ):
        self.context = context
        self.reporter = reporter  # callable(rank, metrics, checkpoint)
        self.dataset_shards = dataset_shards or {}
        self.latest_checkpoint = latest_checkpoint


def init_session(session: _Session) -> None:
    _session_local.session = session


def shutdown_session() -> None:
    _session_local.session = None


def get_session() -> Optional[_Session]:
    return getattr(_session_local, "session", None)


def _require_session() -> _Session:
    s = get_session()
    if s is None:
        raise RuntimeError("Not inside a train worker; train.* session APIs require a running Trainer.")
    return s


# ------------------------------------------------------------ public API
def report(metrics: Dict[str, Any], *, checkpoint=None) -> None:
    """Stream metrics (and optionally a checkpoint) to the driver
    (parity: train.report)."""
    s = _require_session()
    s.reporter(s.context.world_rank, dict(metrics), checkpoint)


def get_context() -> TrainContext:
    return _require_session().context


def get_dataset_shard(name: str = "train"):
    s = _require_session()
    if name not in s.dataset_shards:
        raise KeyError(f"no dataset shard named {name!r}; available: {list(s.dataset_shards)}")
    return s.dataset_shards[name]


def get_checkpoint():
    """The checkpoint to resume from, if the trainer was restored
    (parity: train.get_checkpoint)."""
    return _require_session().latest_checkpoint
