"""XGBoost data-parallel trainer.

Parity: ``python/ray/train/xgboost/xgboost_trainer.py:74`` (per-worker
``xgboost.train`` on the worker's dataset shard, train dataset included in
the eval set so ``train-*`` metrics report), ``train/xgboost/config.py``
(rabit tracker bootstrap — here rank 0 starts the tracker and publishes the
worker args over the cluster KV instead of a backend side channel), and
``train/xgboost/_xgboost_utils.py`` (``RayTrainReportCallback``: per-round
metric reports + model checkpoints through the train session).

Gated on the ``xgboost`` import; everything this module drives is public
xgboost API (``train``, ``DMatrix``, ``Booster``, ``callback
.TrainingCallback``, ``collective.CommunicatorContext``).
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from typing import Any, Dict, List, Optional

from ray_tpu.train import session as train_session
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.predictor import Predictor, wrap_predictions_column
from ray_tpu.train.config import TRAIN_DATASET_KEY
from ray_tpu.train.gbdt import (
    eval_shards,
    host_ip,
    kv_rendezvous,
    require_module,
    shard_to_xy,
)
from ray_tpu.train.trainer import DataParallelTrainer

__all__ = ["XGBoostTrainer", "XGBoostCheckpoint", "RayTrainReportCallback", "XGBoostPredictor"]


class XGBoostCheckpoint(Checkpoint):
    """A checkpoint holding one serialized xgboost Booster."""

    MODEL_FILENAME = "model.json"

    @classmethod
    def from_model(cls, booster, base_dir: Optional[str] = None) -> "XGBoostCheckpoint":
        d = base_dir or tempfile.mkdtemp(prefix="xgb_ckpt_")
        os.makedirs(d, exist_ok=True)
        booster.save_model(os.path.join(d, cls.MODEL_FILENAME))
        return cls(d)

    def get_model(self):
        xgboost = require_module("xgboost")
        booster = xgboost.Booster()
        booster.load_model(os.path.join(self.path, self.MODEL_FILENAME))
        return booster


class RayTrainReportCallback:
    """Per-boosting-round bridge from xgboost into the train session.

    Reports the latest value of every eval metric each round (flattened as
    ``{dataset}-{metric}``) and checkpoints the booster every ``frequency``
    rounds (0 = never mid-train) plus once at the end of training.  Only the
    rank-0 worker writes checkpoints — sibling ranks hold replicas of the
    same boosted model after each allreduce round.
    """

    def __init__(
        self,
        metrics: Optional[List[str]] = None,
        frequency: int = 0,
        checkpoint_at_end: bool = True,
    ):
        self._metrics = metrics
        self._frequency = frequency
        self._checkpoint_at_end = checkpoint_at_end
        self._last_report: Dict[str, Any] = {}

    # -- xgboost TrainingCallback protocol (duck-typed; `_adapt_callback`
    # wraps this in a real TrainingCallback subclass when xgboost is live) --
    def after_iteration(self, model, epoch: int, evals_log) -> bool:
        report: Dict[str, Any] = {"training_iteration": epoch + 1}
        for ds_name, metric_hist in (evals_log or {}).items():
            for metric_name, values in metric_hist.items():
                key = f"{ds_name}-{metric_name}"
                if self._metrics is not None and key not in self._metrics and metric_name not in self._metrics:
                    continue
                report[key] = values[-1]
        self._last_report = report
        ckpt = None
        if self._frequency and (epoch + 1) % self._frequency == 0:
            ckpt = self._maybe_checkpoint(model)
        train_session.report(report, checkpoint=ckpt)
        return False  # never early-stop on the report path

    def after_training(self, model):
        if self._checkpoint_at_end:
            ckpt = self._maybe_checkpoint(model)
            if ckpt is not None:
                train_session.report(dict(self._last_report), checkpoint=ckpt)
        return model

    def before_training(self, model):
        return model

    def before_iteration(self, model, epoch, evals_log) -> bool:
        return False

    def _maybe_checkpoint(self, model) -> Optional[Checkpoint]:
        ctx = train_session.get_context()
        if ctx.get_world_rank() != 0:
            return None
        return XGBoostCheckpoint.from_model(model)

    @classmethod
    def get_model(cls, checkpoint: Checkpoint):
        """Load the booster out of a checkpoint produced by this callback."""
        return XGBoostCheckpoint(checkpoint.path).get_model()


def _adapt_callback(cb: RayTrainReportCallback, xgboost):
    """Wrap our duck-typed callback in a real TrainingCallback subclass —
    xgboost rejects callbacks that don't inherit its base class."""
    base = getattr(getattr(xgboost, "callback", None), "TrainingCallback", None)
    if base is None or isinstance(cb, base):
        return cb

    class _Adapter(base):
        def after_iteration(self, model, epoch, evals_log):
            return cb.after_iteration(model, epoch, evals_log)

        def after_training(self, model):
            return cb.after_training(model)

    return _Adapter()


@contextlib.contextmanager
def _communicator(xgboost, world_size: int, rank: int, run_key: str):
    """Enter xgboost's collective for a multi-worker gang.

    Rank 0 starts the tracker and publishes its worker args over the
    cluster KV; all ranks join a CommunicatorContext so xgboost's histogram
    allreduce spans the gang (reference: ``train/xgboost/config.py``).
    Degrades to per-shard independent training when the installed xgboost
    predates the collective API.
    """
    coll = getattr(xgboost, "collective", None)
    tracker_mod = getattr(xgboost, "tracker", None)
    ctx_cls = getattr(coll, "CommunicatorContext", None) if coll else None
    tracker_cls = getattr(tracker_mod, "RabitTracker", None) if tracker_mod else None
    if world_size <= 1 or ctx_cls is None or tracker_cls is None:
        if world_size > 1:
            import warnings

            warnings.warn(
                "xgboost has no collective API (xgboost.collective / "
                "xgboost.tracker missing): each of the "
                f"{world_size} workers is training INDEPENDENTLY on its "
                "1/{0} row shard — the checkpointed model sees a fraction "
                "of the data. Upgrade xgboost (>=1.7) for distributed "
                "training.".format(world_size),
                RuntimeWarning,
                stacklevel=2,
            )
        yield
        return
    tracker = None
    if rank == 0:
        tracker = tracker_cls(host_ip=host_ip(), n_workers=world_size)
        tracker.start()
        args = {k: v for k, v in tracker.worker_args().items()}
        kv_rendezvous(run_key, rank, world_size, args)
    else:
        payloads = kv_rendezvous(run_key, rank, world_size, {})
        args = payloads[0]
    try:
        with ctx_cls(**args):
            yield
    finally:
        if tracker is not None:
            with contextlib.suppress(Exception):
                tracker.free()


class XGBoostTrainer(DataParallelTrainer):
    """Distributed XGBoost over the train worker gang.

    Each worker trains on its row shard of the ``train`` dataset inside the
    xgboost collective, so the boosted model is identical on every rank;
    every non-train dataset becomes a named eval set (the train set itself
    is always evaluated too, giving the reference's ``train-*`` rows).
    """

    def __init__(
        self,
        *,
        params: Optional[Dict[str, Any]] = None,
        label_column: str,
        num_boost_round: int = 10,
        dmatrix_params: Optional[Dict[str, Dict[str, Any]]] = None,
        xgboost_train_kwargs: Optional[Dict[str, Any]] = None,
        report_callback: Optional[RayTrainReportCallback] = None,
        **kwargs,
    ):
        params = dict(params or {})
        dmatrix_params = dmatrix_params or {}
        train_kwargs = dict(xgboost_train_kwargs or {})
        dataset_keys = set((kwargs.get("datasets") or {}).keys())
        rc = kwargs.get("run_config")
        run_name = (rc.name if rc is not None and rc.name else None) or f"xgb_{os.getpid()}"

        def _train_fn(config: dict):
            xgboost = require_module("xgboost")
            merged = dict(params)
            merged.update(config or {})
            ctx = train_session.get_context()
            world, rank = ctx.get_world_size(), ctx.get_world_rank()

            ckpt = train_session.get_checkpoint()
            starting_model = None
            remaining = num_boost_round
            if ckpt is not None:
                starting_model = XGBoostCheckpoint(ckpt.path).get_model()
                done = int(starting_model.num_boosted_rounds()) if hasattr(
                    starting_model, "num_boosted_rounds"
                ) else 0
                remaining = max(num_boost_round - done, 0)

            cb = report_callback or RayTrainReportCallback()
            callbacks = list(train_kwargs.get("callbacks", []))
            callbacks.append(_adapt_callback(cb, xgboost))
            extra = {k: v for k, v in train_kwargs.items() if k != "callbacks"}
            evals_result: Dict[str, Any] = {}
            rdv_key = f"xgb_tracker/{run_name}/{ctx.get_group_token()}"
            # the communicator spans shard loading too: ranks rendezvous on
            # the tracker BEFORE the (possibly minutes-long, skewed) data
            # materialization, so load skew can't eat the rendezvous timeout
            with _communicator(xgboost, world, rank, rdv_key):
                train_X, train_y = shard_to_xy(
                    train_session.get_dataset_shard(TRAIN_DATASET_KEY), label_column
                )
                dtrain = xgboost.DMatrix(
                    train_X, label=train_y, **dmatrix_params.get(TRAIN_DATASET_KEY, {})
                )
                evals = [(dtrain, TRAIN_DATASET_KEY)]
                for name, X, y in eval_shards(dataset_keys, label_column, TRAIN_DATASET_KEY):
                    evals.append(
                        (xgboost.DMatrix(X, label=y, **dmatrix_params.get(name, {})), name)
                    )
                xgboost.train(
                    merged,
                    dtrain=dtrain,
                    evals=evals,
                    evals_result=evals_result,
                    num_boost_round=remaining,
                    xgb_model=starting_model,
                    callbacks=callbacks,
                    **extra,
                )

        super().__init__(_train_fn, train_loop_config={}, **kwargs)


class XGBoostPredictor(Predictor):
    """Batch inference with a trained booster (parity:
    ``train/xgboost/xgboost_predictor.py:18``)."""

    def __init__(self, model, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, preprocessor=None) -> "XGBoostPredictor":
        return cls(XGBoostCheckpoint(checkpoint.path).get_model(), preprocessor)

    def _predict_pandas(self, df, **kwargs):
        import pandas as pd

        xgboost = require_module("xgboost")
        preds = self.model.predict(xgboost.DMatrix(df), **kwargs)
        return pd.DataFrame({"predictions": wrap_predictions_column(preds)})
