"""Runtime-env plugin architecture.

Parity with ``python/ray/_private/runtime_env/plugin.py``: each runtime_env
field maps to a plugin with validate/create/modify hooks.  Shipped plugins:

  * ``env_vars``    — extra environment variables (validated str→str)
  * ``working_dir`` — a local directory packaged (copied) into the session's
    resource dir and used as the process cwd (``working_dir.py`` parity;
    remote URIs are out of scope with zero egress)
  * ``py_modules``  — local module dirs/files staged and prepended to
    PYTHONPATH (``py_modules.py`` parity)
  * ``pip`` / ``conda`` — declared for API parity; creation raises unless
    the env already satisfies them, since the image has no network

Creation is cached per-URI through :class:`~ray_tpu.runtime_env.uri_cache.URICache`.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tempfile
from typing import Dict, Optional, Tuple

from ray_tpu.runtime_env.uri_cache import URICache

_RESOURCE_DIR = None
_cache = URICache()


def _resource_dir() -> str:
    global _RESOURCE_DIR
    if _RESOURCE_DIR is None:
        _RESOURCE_DIR = os.path.join("/tmp", f"rt_runtime_env_{os.getpid()}")
        os.makedirs(_RESOURCE_DIR, exist_ok=True)
    return _RESOURCE_DIR


class RuntimeEnvPlugin:
    """Base plugin. ``name`` is the runtime_env dict key it owns."""

    name: str = ""
    priority: int = 10

    def validate(self, value) -> None:
        pass

    def create(self, value) -> Optional[str]:
        """Prepare resources; returns a URI for cache bookkeeping (or None)."""
        return None

    def modify_context(
        self,
        value,
        env: Dict[str, str],
        cwd: Optional[str],
        uris: Optional[list] = None,
    ) -> Tuple[Dict[str, str], Optional[str]]:
        """Mutate the process env/cwd the worker or driver will start with.
        Staging plugins append the cache URIs they used to ``uris`` so the
        caller can hold references for the process's lifetime."""
        return env, cwd


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 0

    def validate(self, value) -> None:
        if not isinstance(value, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in value.items()
        ):
            raise TypeError("runtime_env['env_vars'] must be a Dict[str, str]")

    def modify_context(self, value, env, cwd, uris=None):
        env.update(value)
        return env, cwd


def _fingerprint(path: str) -> str:
    """Cheap content fingerprint: relative names + sizes + mtimes. A changed
    source dir therefore yields a new URI and gets re-staged (the reference
    hashes the packaged zip the same way, packaging.py)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.exists(path):
        raise FileNotFoundError(f"runtime_env path does not exist: {path}")
    h = hashlib.sha1(path.encode())
    if os.path.isfile(path):
        st = os.stat(path)
        h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
    else:
        for root, dirs, files in os.walk(path):
            dirs.sort()
            for f in sorted(files):
                fp = os.path.join(root, f)
                try:
                    st = os.stat(fp)
                except OSError:
                    continue
                h.update(f"{os.path.relpath(fp, path)}:{st.st_size}:{st.st_mtime_ns};".encode())
    return h.hexdigest()[:16]


def uri_for(path: str, kind: str) -> str:
    return f"{kind}://{os.path.abspath(os.path.expanduser(path))}@{_fingerprint(path)}"


def _stage_dir(path: str, kind: str) -> str:
    """Copy a local dir/file into the session resource dir, content-addressed;
    the copy lands in a temp dir and is renamed into place so readers never
    see a partial stage."""
    path = os.path.abspath(os.path.expanduser(path))
    h = _fingerprint(path)
    # Keep the artifact's own basename (it must stay importable for
    # py_modules); uniqueness comes from the hashed parent dir.
    parent = os.path.join(_resource_dir(), f"{kind}-{h}")
    dest = os.path.join(parent, os.path.basename(path))
    if not os.path.exists(dest):
        tmp_parent = parent + ".tmp"
        shutil.rmtree(tmp_parent, ignore_errors=True)
        os.makedirs(tmp_parent, exist_ok=True)
        tmp = os.path.join(tmp_parent, os.path.basename(path))
        if os.path.isdir(path):
            shutil.copytree(path, tmp)
        else:
            shutil.copy2(path, tmp)
        try:
            os.rename(tmp_parent, parent)
        except OSError:
            shutil.rmtree(tmp_parent, ignore_errors=True)  # a racer won
    return dest


class WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 1

    def validate(self, value) -> None:
        if not isinstance(value, str):
            raise TypeError("runtime_env['working_dir'] must be a local directory path")

    def create(self, value) -> str:
        return _stage_dir(value, "working_dir")

    def modify_context(self, value, env, cwd, uris=None):
        uri = uri_for(value, "working_dir")
        staged = _cache.get_or_create(uri, lambda: self.create(value), add_ref=uris is not None)
        if uris is not None:
            uris.append(uri)
        return env, staged


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 2

    def validate(self, value) -> None:
        if not isinstance(value, (list, tuple)) or not all(isinstance(v, str) for v in value):
            raise TypeError("runtime_env['py_modules'] must be a list of local paths")

    def modify_context(self, value, env, cwd, uris=None):
        staged_paths = []
        for mod in value:
            uri = uri_for(mod, "py_modules")
            staged = _cache.get_or_create(
                uri, lambda m=mod: _stage_dir(m, "py_modules"), add_ref=uris is not None
            )
            if uris is not None:
                uris.append(uri)
            # a staged package dir's *parent* goes on sys.path
            staged_paths.append(os.path.dirname(staged) if os.path.isdir(staged) else staged)
        existing = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(staged_paths + ([existing] if existing else [])))
        return env, cwd


_DIST_MODULES: Optional[Dict[str, list]] = None


def _dist_module_map() -> Dict[str, list]:
    """distribution name -> importable module(s): "scikit-learn" installs
    "sklearn" etc. Scanning installed-dist metadata is O(100ms); the result
    only changes on (un)install, so compute once per process."""
    global _DIST_MODULES
    if _DIST_MODULES is None:
        import importlib.metadata

        mapping: Dict[str, list] = {}
        try:
            for module, dists in importlib.metadata.packages_distributions().items():
                for d in dists:
                    mapping.setdefault(d.lower().replace("_", "-"), []).append(module)
        except Exception:
            pass
        _DIST_MODULES = mapping
    return _DIST_MODULES


class PipPlugin(RuntimeEnvPlugin):
    """Parity with ``pip.py:425``; the zero-egress image cannot install, so
    creation verifies the requirements are already importable and otherwise
    raises with a clear message."""

    name = "pip"
    priority = 3

    def validate(self, value) -> None:
        if not isinstance(value, (list, dict)):
            raise TypeError("runtime_env['pip'] must be a list of requirements or a dict")

    def modify_context(self, value, env, cwd, uris=None):
        import importlib.util

        dist_modules = _dist_module_map()
        reqs = value if isinstance(value, list) else value.get("packages", [])
        missing = []
        for req in reqs:
            base = req.split("==")[0].split(">=")[0].split("<")[0].strip()
            candidates = list(dist_modules.get(base.lower().replace("_", "-"), []))
            candidates.append(base.replace("-", "_"))
            if not any(importlib.util.find_spec(c) is not None for c in candidates):
                missing.append(req)
        if missing:
            raise RuntimeError(
                f"runtime_env pip packages not pre-installed and the environment "
                f"has no network access: {missing}"
            )
        return env, cwd


class CondaPlugin(RuntimeEnvPlugin):
    """Parity with ``conda.py:259``; like pip, the zero-egress image cannot
    solve/install environments, so the plugin validates shape and verifies
    any pip-style dependency list is already importable."""

    name = "conda"
    priority = 3

    def validate(self, value) -> None:
        if not isinstance(value, (str, dict)):
            raise TypeError(
                "runtime_env['conda'] must be an env name or an environment.yml dict"
            )

    def modify_context(self, value, env, cwd, uris=None):
        if isinstance(value, str):
            raise RuntimeError(
                f"runtime_env conda env {value!r}: no conda installation is "
                "available in this environment"
            )
        deps = value.get("dependencies", [])
        reqs = []
        for d in deps:
            if isinstance(d, dict) and "pip" in d:
                reqs.extend(d["pip"])
            elif isinstance(d, str) and d.split("=")[0] not in ("python", "pip"):
                # conda-native packages verify the same way: importable or
                # fail fast with the clear not-pre-installed error
                reqs.append(d.split("=")[0])
        if reqs:
            return PipPlugin().modify_context(reqs, env, cwd, uris)
        return env, cwd


class ContainerPlugin(RuntimeEnvPlugin):
    """Run the job entrypoint inside a container (reference:
    ``python/ray/_private/runtime_env/container.py`` — podman-wrapped worker
    commands).  Value shape::

        {"image": "img:tag", "run_options": ["--net=host", ...]}

    The container engine is resolved at validate time (podman preferred,
    docker fallback); the repo/working dir is bind-mounted so staged
    runtime-env artifacts stay visible."""

    name = "container"
    priority = 90  # wraps last: sees the final env/cwd

    def _engine(self) -> Optional[str]:
        import shutil as _shutil

        for exe in ("podman", "docker"):
            if _shutil.which(exe):
                return exe
        return None

    def validate(self, value) -> None:
        if not isinstance(value, dict) or "image" not in value:
            raise ValueError("runtime_env['container'] must be {'image': ..., ...}")
        if self._engine() is None:
            raise ValueError(
                "runtime_env['container'] requires podman or docker on PATH"
            )

    def wrap_entrypoint(
        self, value, entrypoint: str, env: Dict[str, str], cwd: Optional[str],
        runtime_env: Optional[dict] = None,
    ) -> str:
        import shlex

        engine = self._engine()
        workdir = cwd or os.getcwd()
        # forward exactly the user's env_vars (host PYTHONPATH etc. would be
        # dangling paths inside the image — the image must ship its own
        # Python environment, reference container.py behavior)
        user_env = (runtime_env or {}).get("env_vars", {})
        parts = [engine, "run", "--rm"]
        parts.extend(shlex.quote(o) for o in value.get("run_options", ()))
        parts.extend(["-v", f"{shlex.quote(workdir)}:/work", "-w", "/work"])
        for k, v in user_env.items():
            parts.extend(["-e", shlex.quote(f"{k}={v}")])
        parts.extend([shlex.quote(value["image"]), "/bin/sh", "-c", shlex.quote(entrypoint)])
        # join non-empty parts with single spaces: a post-hoc
        # .replace("  ", " ") would corrupt double spaces INSIDE quoted values
        return " ".join(p for p in parts if p)


class MPIPlugin(RuntimeEnvPlugin):
    """Wrap the entrypoint in ``mpirun`` (reference:
    ``python/ray/_private/runtime_env/mpi.py:41`` ``MPIPlugin`` wrapping
    worker exec in mpirun :104).  Value shape::

        {"worker_entry": ..., "args": ["-n", "4"]}  # or {"processes": 4}
    """

    name = "mpi"
    priority = 80

    def validate(self, value) -> None:
        if not isinstance(value, dict):
            raise ValueError("runtime_env['mpi'] must be a dict")
        import shutil as _shutil

        if _shutil.which("mpirun") is None:
            raise ValueError("runtime_env['mpi'] requires mpirun on PATH")

    def wrap_entrypoint(
        self, value, entrypoint: str, env: Dict[str, str], cwd: Optional[str],
        runtime_env: Optional[dict] = None,
    ) -> str:
        import shlex

        if "args" in value:
            args = " ".join(shlex.quote(a) for a in value["args"])
        else:
            args = f"-n {int(value.get('processes', 1))}"
        return f"mpirun {args} /bin/sh -c {shlex.quote(entrypoint)}"


class ProfilingPlugin(RuntimeEnvPlugin):
    """Per-task cProfile capture (reference role: the profiling runtime-env
    plugins — ``_private/runtime_env/nsight.py`` shape, py-spy dashboard
    integration — rebuilt CPU-native: TPU work is profiled by
    ``jax.profiler``, what needs a runtime-env switch is the PYTHON side of
    a task).  Value shape::

        {"profiling": True}                      # profiles to the session dir
        {"profiling": {"dir": "/tmp/profs"}}     # explicit output dir

    Workers honor ``RAY_TPU_TASK_PROFILING``: every task/actor-call body
    runs under cProfile and dumps ``<name>_<task_id>.prof`` (pstats
    loadable) into the directory.  Zero overhead when unset."""

    name = "profiling"
    priority = 5

    def validate(self, value) -> None:
        if not (value is True or isinstance(value, dict)):
            raise ValueError("runtime_env['profiling'] must be True or {'dir': path}")
        if isinstance(value, dict) and set(value) - {"dir"}:
            raise ValueError(f"unknown profiling keys {set(value) - {'dir'}}")

    def modify_context(self, value, env, cwd, uris=None):
        out_dir = value.get("dir") if isinstance(value, dict) else None
        if not out_dir:
            out_dir = os.path.join(tempfile.gettempdir(), "rt_task_profiles")
        os.makedirs(out_dir, exist_ok=True)
        env["RAY_TPU_TASK_PROFILING"] = out_dir
        return env, cwd


def maybe_profile(name: str, task_id_hex: str, fn, args, kwargs):
    """Worker-side hook for ProfilingPlugin: run a task body under cProfile
    when RAY_TPU_TASK_PROFILING is set, dumping a pstats-loadable file per
    task.  One getenv when profiling is off."""
    out_dir = os.environ.get("RAY_TPU_TASK_PROFILING")
    if not out_dir:
        return fn(*args, **kwargs)
    import cProfile
    import re

    prof = cProfile.Profile()
    try:
        return prof.runcall(fn, *args, **kwargs)
    finally:
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", name or "task")[:60]
        try:
            os.makedirs(out_dir, exist_ok=True)  # this process may not be the creator
            prof.dump_stats(os.path.join(out_dir, f"{safe}_{task_id_hex[:12]}.prof"))
        except OSError as exc:
            # profiling must never fail the task — but silence here means
            # "profiling on, zero profiles, no clue"; say why once
            import logging

            logging.getLogger(__name__).warning(
                "profiling dump to %s failed: %s", out_dir, exc
            )


def wrap_entrypoint(
    runtime_env: dict, entrypoint: str, env: Dict[str, str], cwd: Optional[str]
) -> str:
    """Apply every command-wrapping plugin (mpi, container) to a job
    entrypoint, in priority order."""
    for key in sorted(runtime_env, key=lambda k: getattr(_plugins.get(k), "priority", 10)):
        plugin = _plugins.get(key)
        if plugin is not None and hasattr(plugin, "wrap_entrypoint"):
            entrypoint = plugin.wrap_entrypoint(
                runtime_env[key], entrypoint, env, cwd, runtime_env=runtime_env
            )
    return entrypoint


_plugins: Dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    _plugins[plugin.name] = plugin


def get_plugin(name: str) -> Optional[RuntimeEnvPlugin]:
    return _plugins.get(name)


for _p in (
    EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin(), PipPlugin(),
    CondaPlugin(), ContainerPlugin(), MPIPlugin(), ProfilingPlugin(),
):
    register_plugin(_p)


# meta keys that configure env setup itself rather than naming a plugin
# (parity: runtime_env["config"] = RuntimeEnvConfig — runtime_env.py)
_META_KEYS = frozenset({"config"})


def validate_runtime_env(runtime_env: dict) -> None:
    for key, value in runtime_env.items():
        if key in _META_KEYS:
            continue
        plugin = _plugins.get(key)
        if plugin is None:
            raise ValueError(f"unknown runtime_env field {key!r}; known: {sorted(_plugins)}")
        plugin.validate(value)


def apply_to_process_env(
    runtime_env: dict,
    env: Dict[str, str],
    cwd: Optional[str] = None,
    uris_out: Optional[list] = None,
) -> Tuple[Dict[str, str], Optional[str]]:
    """Run every relevant plugin's modify_context, in priority order.

    Pass ``uris_out`` to collect the cache URIs the env uses; each staged
    artifact is reference-pinned atomically as it is handed out, so eviction
    never deletes a directory a live job is running from. Release with
    :func:`remove_references` when the process exits.
    """
    validate_runtime_env(runtime_env)
    for plugin in sorted(
        (_plugins[k] for k in runtime_env if k not in _META_KEYS),
        key=lambda p: p.priority,
    ):
        env, cwd = plugin.modify_context(runtime_env[plugin.name], env, cwd, uris_out)
    return env, cwd


def add_references(uris: list) -> None:
    for uri in uris:
        _cache.add_reference(uri)


def remove_references(uris: list) -> None:
    for uri in uris:
        _cache.remove_reference(uri)
