"""Runtime-env plugin architecture.

Parity with ``python/ray/_private/runtime_env/plugin.py``: each runtime_env
field maps to a plugin with validate/create/modify hooks.  Shipped plugins:

  * ``env_vars``    — extra environment variables (validated str→str)
  * ``working_dir`` — a local directory packaged (copied) into the session's
    resource dir and used as the process cwd (``working_dir.py`` parity;
    remote URIs are out of scope with zero egress)
  * ``py_modules``  — local module dirs/files staged and prepended to
    PYTHONPATH (``py_modules.py`` parity)
  * ``pip`` / ``conda`` — declared for API parity; creation raises unless
    the env already satisfies them, since the image has no network

Creation is cached per-URI through :class:`~ray_tpu.runtime_env.uri_cache.URICache`.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Dict, Optional, Tuple

from ray_tpu.runtime_env.uri_cache import URICache

_RESOURCE_DIR = None
_cache = URICache()


def _resource_dir() -> str:
    global _RESOURCE_DIR
    if _RESOURCE_DIR is None:
        _RESOURCE_DIR = os.path.join("/tmp", f"rt_runtime_env_{os.getpid()}")
        os.makedirs(_RESOURCE_DIR, exist_ok=True)
    return _RESOURCE_DIR


class RuntimeEnvPlugin:
    """Base plugin. ``name`` is the runtime_env dict key it owns."""

    name: str = ""
    priority: int = 10

    def validate(self, value) -> None:
        pass

    def create(self, value) -> Optional[str]:
        """Prepare resources; returns a URI for cache bookkeeping (or None)."""
        return None

    def modify_context(self, value, env: Dict[str, str], cwd: Optional[str]) -> Tuple[Dict[str, str], Optional[str]]:
        """Mutate the process env/cwd the worker or driver will start with."""
        return env, cwd


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 0

    def validate(self, value) -> None:
        if not isinstance(value, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in value.items()
        ):
            raise TypeError("runtime_env['env_vars'] must be a Dict[str, str]")

    def modify_context(self, value, env, cwd):
        env.update(value)
        return env, cwd


def _stage_dir(path: str, kind: str) -> str:
    """Copy a local dir/file into the session resource dir, content-addressed
    (the reference packages to a zip URI and unpacks into a per-URI dir)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.exists(path):
        raise FileNotFoundError(f"runtime_env path does not exist: {path}")
    h = hashlib.sha1(path.encode()).hexdigest()[:16]
    # Keep the artifact's own basename (it must stay importable for
    # py_modules); uniqueness comes from the hashed parent dir.
    dest = os.path.join(_resource_dir(), f"{kind}-{h}", os.path.basename(path))
    if not os.path.exists(dest):
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if os.path.isdir(path):
            shutil.copytree(path, dest)
        else:
            shutil.copy2(path, dest)
    return dest


class WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 1

    def validate(self, value) -> None:
        if not isinstance(value, str):
            raise TypeError("runtime_env['working_dir'] must be a local directory path")

    def create(self, value) -> str:
        return _stage_dir(value, "working_dir")

    def modify_context(self, value, env, cwd):
        staged = _cache.get_or_create(f"working_dir://{value}", lambda: self.create(value))
        return env, staged


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 2

    def validate(self, value) -> None:
        if not isinstance(value, (list, tuple)) or not all(isinstance(v, str) for v in value):
            raise TypeError("runtime_env['py_modules'] must be a list of local paths")

    def modify_context(self, value, env, cwd):
        staged_paths = []
        for mod in value:
            staged = _cache.get_or_create(f"py_modules://{mod}", lambda m=mod: _stage_dir(m, "py_modules"))
            # a staged package dir's *parent* goes on sys.path
            staged_paths.append(os.path.dirname(staged) if os.path.isdir(staged) else staged)
        existing = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(staged_paths + ([existing] if existing else [])))
        return env, cwd


class PipPlugin(RuntimeEnvPlugin):
    """Parity with ``pip.py:425``; the zero-egress image cannot install, so
    creation verifies the requirements are already importable and otherwise
    raises with a clear message."""

    name = "pip"
    priority = 3

    def validate(self, value) -> None:
        if not isinstance(value, (list, dict)):
            raise TypeError("runtime_env['pip'] must be a list of requirements or a dict")

    def modify_context(self, value, env, cwd):
        import importlib.util

        reqs = value if isinstance(value, list) else value.get("packages", [])
        missing = []
        for req in reqs:
            base = req.split("==")[0].split(">=")[0].split("<")[0].strip().replace("-", "_")
            if importlib.util.find_spec(base) is None:
                missing.append(req)
        if missing:
            raise RuntimeError(
                f"runtime_env pip packages not pre-installed and the environment "
                f"has no network access: {missing}"
            )
        return env, cwd


_plugins: Dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    _plugins[plugin.name] = plugin


def get_plugin(name: str) -> Optional[RuntimeEnvPlugin]:
    return _plugins.get(name)


for _p in (EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin(), PipPlugin()):
    register_plugin(_p)


def validate_runtime_env(runtime_env: dict) -> None:
    for key, value in runtime_env.items():
        plugin = _plugins.get(key)
        if plugin is None:
            raise ValueError(f"unknown runtime_env field {key!r}; known: {sorted(_plugins)}")
        plugin.validate(value)


def apply_to_process_env(
    runtime_env: dict, env: Dict[str, str], cwd: Optional[str] = None
) -> Tuple[Dict[str, str], Optional[str]]:
    """Run every relevant plugin's modify_context, in priority order."""
    validate_runtime_env(runtime_env)
    for plugin in sorted(
        (_plugins[k] for k in runtime_env), key=lambda p: p.priority
    ):
        env, cwd = plugin.modify_context(runtime_env[plugin.name], env, cwd)
    return env, cwd
