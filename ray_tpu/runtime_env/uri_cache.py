"""URI-addressed resource cache with reference counting.

Parity with ``python/ray/_private/runtime_env/uri_cache.py``: created
runtime-env artifacts (staged working dirs, py_modules) are cached by URI;
refcounts track live users and size-bounded eviction deletes unreferenced
artifacts oldest-first.
"""

from __future__ import annotations

import os
import shutil
import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional


def _dir_size(path: str) -> int:
    if os.path.isfile(path):
        return os.path.getsize(path)
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


class URICache:
    def __init__(self, max_total_size_bytes: int = 10 * 1024**3):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, str]" = OrderedDict()  # uri -> local path
        self._refs: Dict[str, int] = {}
        self._sizes: Dict[str, int] = {}
        self._creation_locks: Dict[str, threading.Lock] = {}
        self.max_total_size_bytes = max_total_size_bytes

    def get_or_create(self, uri: str, creator: Callable[[], str], add_ref: bool = False) -> str:
        """Return the artifact path, creating it if absent.

        ``add_ref=True`` takes a reference atomically with the lookup, so no
        eviction window exists between handing out the path and the caller
        pinning it (pair with :meth:`remove_reference`).
        """
        # Serialize creation per URI so two concurrent submissions with the
        # same new artifact don't both run the creator (and race the copy).
        with self._lock:
            creation_lock = self._creation_locks.setdefault(uri, threading.Lock())
        with creation_lock:
            with self._lock:
                path = self._entries.get(uri)
                if path is not None and os.path.exists(path):
                    self._entries.move_to_end(uri)
                    if add_ref:
                        self._refs[uri] = self._refs.get(uri, 0) + 1
                    return path
            try:
                path = creator()
                with self._lock:
                    self._entries[uri] = path
                    self._sizes[uri] = _dir_size(path)
                    if add_ref:
                        self._refs[uri] = self._refs.get(uri, 0) + 1
                    self._evict_locked()
                return path
            finally:
                # prune the per-URI lock: fingerprinted URIs are minted per
                # content version, so keeping them would grow without bound
                with self._lock:
                    self._creation_locks.pop(uri, None)

    def add_reference(self, uri: str) -> None:
        with self._lock:
            self._refs[uri] = self._refs.get(uri, 0) + 1

    def remove_reference(self, uri: str) -> None:
        with self._lock:
            n = self._refs.get(uri, 0) - 1
            if n <= 0:
                self._refs.pop(uri, None)
            else:
                self._refs[uri] = n
            self._evict_locked()

    def get(self, uri: str) -> Optional[str]:
        with self._lock:
            return self._entries.get(uri)

    def describe(self):
        """Cache rows for the state API: uri, local path, refs, bytes."""
        with self._lock:
            return [
                {
                    "uri": uri,
                    "path": path,
                    "ref_count": self._refs.get(uri, 0),
                    "size_bytes": self._sizes.get(uri, 0),
                }
                for uri, path in self._entries.items()
            ]

    def total_size(self) -> int:
        with self._lock:
            return sum(self._sizes.values())

    def _evict_locked(self) -> None:
        total = sum(self._sizes.values())
        for uri in list(self._entries):
            if total <= self.max_total_size_bytes:
                break
            if self._refs.get(uri, 0) > 0:
                continue
            path = self._entries.pop(uri)
            total -= self._sizes.pop(uri, 0)
            shutil.rmtree(path, ignore_errors=True)
