"""RuntimeEnv / RuntimeEnvConfig classes (parity:
``python/ray/runtime_env/runtime_env.py`` — the dict-like user-facing
config objects) and ``mpi_init`` (``python/ray/runtime_env/mpi.py``)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.runtime_env.plugin import validate_runtime_env


class RuntimeEnvConfig(dict):
    """Execution knobs for env setup itself (parity: RuntimeEnvConfig)."""

    def __init__(
        self,
        setup_timeout_seconds: int = 600,
        eager_install: bool = True,
    ):
        super().__init__(
            setup_timeout_seconds=setup_timeout_seconds,
            eager_install=eager_install,
        )

    @property
    def setup_timeout_seconds(self) -> int:
        return self["setup_timeout_seconds"]

    @property
    def eager_install(self) -> bool:
        return self["eager_install"]


class RuntimeEnv(dict):
    """Dict-like runtime environment (parity: ray.runtime_env.RuntimeEnv).
    Fields validate on construction through the plugin registry, so a typo'd
    key fails at definition time, not at worker start."""

    def __init__(self, **fields: Any):
        config = fields.pop("config", None)
        validate_runtime_env({k: v for k, v in fields.items()})
        super().__init__(**fields)
        if config is not None:
            self["config"] = (
                config if isinstance(config, RuntimeEnvConfig) else RuntimeEnvConfig(**config)
            )

    def to_dict(self) -> Dict[str, Any]:
        return dict(self)

    def plugin_uris(self) -> list:
        return [v for k, v in self.items() if isinstance(v, str) and "://" in v]


def mpi_init() -> Optional[Any]:
    """Initialize MPI inside an ``mpi`` runtime-env worker (parity:
    ``ray.runtime_env.mpi_init`` — the entrypoint the reference tells MPI
    jobs to call first). Returns the COMM_WORLD communicator."""
    try:
        from mpi4py import MPI  # type: ignore[import-not-found]
    except ImportError as exc:
        raise ImportError(
            "mpi_init() needs mpi4py inside the worker; declare "
            'runtime_env={"pip": ["mpi4py"], "mpi": {...}} on the task/actor'
        ) from exc
    if not MPI.Is_initialized():
        MPI.Init()
    return MPI.COMM_WORLD
