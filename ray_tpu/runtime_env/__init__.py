"""Runtime environments: per-job/task/actor execution environments.

Parity with ``python/ray/_private/runtime_env/``: a plugin architecture
(``plugin.py``) where each field of the ``runtime_env`` dict (env_vars,
working_dir, py_modules, …) is handled by a named plugin that prepares
resources and mutates the worker/driver process context; URI-addressed
artifacts are cached with reference counting (``uri_cache.py``).
"""

from ray_tpu.runtime_env.plugin import (
    RuntimeEnvPlugin,
    apply_to_process_env,
    get_plugin,
    register_plugin,
    validate_runtime_env,
)
from ray_tpu.runtime_env.runtime_env import RuntimeEnv, RuntimeEnvConfig, mpi_init
from ray_tpu.runtime_env.uri_cache import URICache

__all__ = [
    "RuntimeEnv",
    "RuntimeEnvConfig",
    "RuntimeEnvPlugin",
    "apply_to_process_env",
    "get_plugin",
    "mpi_init",
    "register_plugin",
    "validate_runtime_env",
    "URICache",
]
