"""Small concurrency helpers shared by the runtime."""

from __future__ import annotations

import threading
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")


def when_all(
    items: Iterable[T],
    start: Callable[[T, Callable[[], None]], None],
    then: Callable[[], None],
) -> None:
    """Countdown barrier: ``start(item, done)`` is called for each item and
    must eventually invoke ``done``; ``then`` fires exactly once after all
    items complete.  With no items, ``then`` fires immediately."""
    items = list(items)
    if not items:
        then()
        return
    remaining = len(items)
    lock = threading.Lock()

    def done(*_ignored) -> None:
        nonlocal remaining
        with lock:
            remaining -= 1
            last = remaining == 0
        if last:
            then()

    for item in items:
        start(item, done)
