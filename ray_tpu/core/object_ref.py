"""ObjectRef: a first-class future handle to an immutable object.

Parity with the reference (``python/ray/includes/object_ref.pxi`` +
``src/ray/core_worker/reference_count.h``): refs participate in distributed
reference counting — creating/copying a ref increments the owner's local
count, ``__del__`` decrements it, and pickling a ref into a task argument
registers the receiver as a borrower via the serialization context.

TPU-first delta: a ref whose value is a ``jax.Array`` resolves to the
HBM-resident array itself (zero-copy) — the ref is the handle XLA-async
dispatch hides latency behind, so ``.result()`` only blocks when the value is
actually needed on host.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Any, Optional

from ray_tpu.core.ids import ObjectID

if TYPE_CHECKING:
    from concurrent.futures import Future

# The live worker hook; set by the runtime at init so ObjectRef.__del__ and
# pickling can reach the reference counter without import cycles.
_worker_hooks = threading.local()


class _GlobalHooks:
    ref_counter = None      # ReferenceCounter
    serialization_ctx = None


hooks = _GlobalHooks()


class ObjectRef:
    __slots__ = ("_id", "_owner", "_skip_decref", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: Optional[str] = None, *, _add_ref: bool = True):
        self._id = object_id
        self._owner = owner_address
        self._skip_decref = not _add_ref
        if _add_ref and hooks.ref_counter is not None:
            hooks.ref_counter.add_local_reference(object_id)

    # -- identity ---------------------------------------------------------
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    def owner_address(self) -> Optional[str]:
        return self._owner

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    # -- future protocol --------------------------------------------------
    def future(self) -> "Future":
        from ray_tpu.runtime.worker import global_worker

        return global_worker().get_async(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    # -- lifecycle --------------------------------------------------------
    def _copy(self) -> "ObjectRef":
        return ObjectRef(self._id, self._owner)

    def __del__(self):
        # __del__ can run at any GC point, including while runtime locks are
        # held — only a lock-free enqueue is safe here.
        if not self._skip_decref and hooks.ref_counter is not None:
            try:
                hooks.ref_counter.enqueue_local_ref_removal(self._id)
            except Exception:
                pass

    def __reduce__(self):
        # Record the ref in the active serialization capture (borrower
        # protocol) and re-increment on the receiving side.
        if hooks.serialization_ctx is not None:
            hooks.serialization_ctx.note_ref(self)
        return (_rebuild_object_ref, (self._id.binary(), self._owner))


def _rebuild_object_ref(id_binary: bytes, owner: Optional[str]) -> ObjectRef:
    return ObjectRef(ObjectID(id_binary), owner)
