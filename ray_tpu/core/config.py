"""Runtime configuration knobs, every one overridable via environment variable.

Parity with the reference's ``RAY_CONFIG`` macro system
(``src/ray/common/ray_config_def.h`` — 218 env-overridable knobs): each field
declared on :class:`Config` can be overridden with ``RAY_TPU_<NAME>`` in the
environment, or programmatically via the ``_system_config`` dict passed to
``ray_tpu.init``.  Unlike the reference there is no C++/Python split to keep in
sync — one dataclass is the single source of truth.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict

_ENV_PREFIX = "RAY_TPU_"


@dataclasses.dataclass
class Config:
    # ---- object store ----------------------------------------------------
    # Max bytes of HBM the object table may pin before spilling to host.
    # 0 = auto (fraction of device memory).
    object_store_hbm_bytes: int = 0
    # Fraction of per-device HBM usable by the object store when auto.
    object_store_hbm_fraction: float = 0.35
    # Host-RAM tier capacity before spilling to the native shm store / disk.
    object_store_host_bytes: int = 8 * 1024**3
    # Chunk size for inter-host object transfer (reference: 5MiB chunks,
    # ray_config_def.h:352).
    object_transfer_chunk_bytes: int = 8 * 1024 * 1024
    # Directory for disk spill (last tier).
    spill_dir: str = "/tmp/ray_tpu_spill"

    # ---- scheduler -------------------------------------------------------
    # Hybrid policy spread threshold (reference hybrid_scheduling_policy.cc:48).
    scheduler_spread_threshold: float = 0.5
    # Top-k random choice among best nodes.
    scheduler_top_k_fraction: float = 0.2
    # Locality-aware placement (reference: locality_with_output /
    # LocalityAwareLeasePolicy, lease_policy.cc): for the default and SPREAD
    # strategies, a task is steered onto the node already holding the most
    # of its dependency bytes when that node leads the runner-up by at
    # least this margin.  0 disables the locality stage.
    scheduler_locality_threshold_bytes: int = 1024 * 1024

    # ---- workers ---------------------------------------------------------
    # CPU-task worker processes prestarted (off-thread) at node start; the
    # pool grows on demand past this, also without blocking submitters.
    num_prestart_workers: int = 1
    # Soft cap on idle workers kept alive per runtime env.
    idle_worker_cap: int = 8
    # Seconds before an idle worker process is reaped.
    idle_worker_timeout_s: float = 60.0

    # ---- control-plane persistence (GCS-with-Redis parity) --------------
    # When set, durable control state (KV, jobs, task events) snapshots to
    # this file periodically and reloads on the next init.
    control_snapshot_path: str = ""
    control_snapshot_interval_s: float = 10.0

    # ---- tasks / fault tolerance ----------------------------------------
    # Adaptive tiering: "auto" tasks whose observed mean wall time exceeds
    # this run in process workers (GIL-free parallelism); faster ones stay
    # on the zero-IPC in-process executor.
    inproc_task_threshold_s: float = 0.002
    # Optional defer before the inproc executor claims a queued task, giving
    # a sync waiter time to steal it inline. 0 (default): claim immediately
    # — stealing usually wins the race anyway and the delay throttles
    # async-burst drains.
    inproc_claim_delay_s: float = 0.0
    # Default max retries for normal tasks (reference default 3).
    task_max_retries: int = 3
    # Default max restarts for actors.
    actor_max_restarts: int = 0
    # Max bytes of lineage kept per worker (reference max_lineage_bytes).
    max_lineage_bytes: int = 1024**3
    # Health-check period / failure threshold.  Tolerance matches the
    # reference's GCS defaults (~25 s before a silent raylet is declared
    # dead: period 3 s x threshold 5 + 10 s ping timeout,
    # ray_config_def.h health_check_*_ms): period * threshold of report
    # silence, then one ping with a health_check_ping_timeout_s budget.
    # The old 1 s x 5 + 2 s ping (~7 s) false-positived on saturated
    # 1-core hosts: a node mid-1 GiB-transfer can starve its report
    # thread past 7 s and get killed while perfectly healthy.
    health_check_period_s: float = 3.0
    health_check_failure_threshold: int = 5
    health_check_ping_timeout_s: float = 10.0
    # How long an unschedulable task waits for capacity (e.g. autoscaler
    # scale-up) before failing as infeasible.
    infeasible_task_timeout_s: float = 30.0
    # Host-memory OOM guard (reference memory_monitor_refresh_ms /
    # memory_usage_threshold, ray_config_def.h). 0 disables the monitor.
    memory_monitor_refresh_ms: int = 250
    memory_usage_threshold: float = 0.95

    # Raise the cyclic-GC thresholds at init (restored at shutdown).
    # Measured: removes periodic 3x submit-throughput collapses caused by
    # collections firing every 700 allocations mid-burst.  Cycles are
    # still collected — just amortized over bursts.
    gc_tune_on_init: bool = True

    # ---- failpoints / chaos ----------------------------------------------
    # Deterministic fault-injection spec (runtime/failpoints.py), e.g.
    # "data_plane.send_frame=drop(0.05);rpc.call=delay(0.2,0.5)".  Empty =
    # everything disarmed (the near-zero-cost default).  The env form
    # (RAY_TPU_FAILPOINTS) is inherited by worker processes and the config
    # form propagates to node agents at registration, so one spec covers
    # the whole fabric.
    failpoints: str = ""
    # Seed of the failpoint decision stream: same (seed, spec, workload) ->
    # byte-for-byte identical fault log (failpoints.fault_log()).
    failpoint_seed: int = 0

    # ---- events / tracing ------------------------------------------------
    task_events_enabled: bool = True
    # Bounded task-event store size (reference GcsTaskManager eviction).
    task_events_max_entries: int = 100_000
    # Distributed task tracing: trace-context propagation through task specs
    # and per-phase spans (submit/schedule/execute/commit) merged into
    # ray_tpu.timeline().  Cheap (a few dict builds per task); disable to
    # shave the last microseconds off the submit hot path.
    tracing_enabled: bool = True

    # ---- distributed -----------------------------------------------------
    # Port for the TCP control service when serving multi-host
    # (start_head_service).  0 = OS-assigned ephemeral port; set it for a
    # stable `rt start --address` target across head restarts.
    control_port: int = 0
    # ray_syncer-equivalent resource broadcast period.
    resource_sync_period_s: float = 0.1
    # Values at or below this size ride the (ordered, low-latency) control
    # connection; larger ones move peer-to-peer on the chunked data plane so
    # bulk bytes never head-of-line-block heartbeats or dispatch.
    data_plane_inline_bytes: int = 64 * 1024
    # Admission control: concurrent bulk transfers served/issued per process
    # (reference: PullManager admission, pull_manager.h:52).
    max_concurrent_object_transfers: int = 4
    # PullManager admission: total bytes of in-flight dependency pulls the
    # fabric allows before further pulls queue (reference:
    # pull_manager.h:52 num_bytes_available_).  Pulls of unknown-size
    # objects are admitted without charging the budget.
    pull_manager_max_inflight_bytes: int = 1 << 30
    # First retry delay after a failed pull source (doubles per attempt,
    # capped at ~2s); the failed location is purged before re-resolving.
    pull_manager_retry_backoff_s: float = 0.05
    # Broadcast: concurrent pulls of ONE object to >= 2 destinations
    # coalesce into a bounded-fanout spanning tree (Cornet/Orchestra-style
    # cooperative broadcast) — the source serves at most this many direct
    # children; every completed destination relays further copies.  0
    # disables the planner (every pull goes straight to a replica).
    broadcast_fanout: int = 2
    # Serve-side frame cache on each data server: N consumers of one bulk
    # object cost one serialization, not N.  Entry count, 0 disables.
    data_server_frame_cache_entries: int = 4
    # Worker results/args decoded from the shm arena stay as READ-ONLY
    # zero-copy views pinned until garbage-collected (plasma Get semantics,
    # plasma/client.h:62) instead of being copied out. Disable for owned,
    # writable arrays at one extra memcpy per bulk value.
    zero_copy_shm_values: bool = True
    # Same-host peers hand bulk objects through the native shm arena
    # (one memcpy, zero socket bytes) instead of loopback TCP — plasma's
    # zero-copy local sharing role (reference: plasma/store.h:55, fd
    # passing fling.cc). Disable to force every transfer onto sockets.
    same_host_shm_transfer: bool = True
    # Compiled execution plans (dag/plan.py): per-frame timeout of the
    # persistent chan_push channel streams AND the inbound-slot delivery
    # wait.  A full consumer slot stalls the producer's ack this long
    # before the stream (and the plan) is declared wedged.
    compiled_plan_channel_timeout_s: float = 300.0
    # Channel kind for compiled-plan edges.  "auto" (and its alias
    # "device"): an edge whose payload is a jax array stays HBM-resident —
    # co-located handoffs are reference moves, cross-host frames carry a
    # control-only header with the payload bypassing pickle entirely
    # (device-to-device pull when a transfer server is up, raw host-staged
    # bytes otherwise); non-array payloads fall back to the pickle path
    # per-edge, per-seq.  "pickle" forces every edge onto the original
    # pickle-5 frame path.
    plan_channel_kind: str = "auto"
    # Producer-side staging depth for cross-host device edges: True keeps
    # the last TWO seqs' arrays staged for pull (seq-parity slots), so a
    # late or retried consumer pull can still fetch seq N-1 while seq N
    # stages — the double-buffering of the mutable-channel design.  False
    # stages one seq at a time.
    device_channel_double_buffer: bool = True
    # Upper bound on SPMD stage-group fan-out (members per gang stage).
    # Each iteration dispatches one member step per gang slot from the
    # stage executor's pool; compile rejects larger groups.
    plan_stage_group_max_members: int = 64
    # Default timeout for one actor-collective round (rendezvous + reduce).
    # Callers waiting on a collective result (rt.get) should budget MORE
    # than this so the collective's own timeout fires first with the
    # precise error, not the outer get's generic one.
    collective_timeout_s: float = 120.0
    # Head fault tolerance: how long a node agent keeps retrying the head
    # after a disconnect before giving up and exiting (reference: raylets
    # reconnect to a restarted GCS — core_worker.proto:443
    # RayletNotifyGCSRestart). 0 restores the round-2 exit-on-disconnect.
    agent_reconnect_timeout_s: float = 60.0
    # Graceful node drain (Cluster.drain_node, DrainRaylet parity): budget
    # for evacuating sole-replica objects AND for the node's in-flight
    # tasks to finish before the terminate lands anyway.
    drain_node_timeout_s: float = 30.0
    # Compiled-plan self-healing: how long repair() (and the auto-repair
    # thread of plans compiled with auto_repair=True) waits for each dead
    # stage actor to come back ALIVE through the restart FSM.
    compiled_plan_repair_timeout_s: float = 30.0

    # ---- worker leases / direct dispatch (runtime/scheduler.LeaseManager,
    # reference: cached RequestWorkerLease reuse per SchedulingKey,
    # direct_task_transport.cc:409) -----------------------------------------
    # How long an unused lease survives before it is returned (its pinned
    # worker goes back to the pool and the next submit re-grants). 0
    # disables lease caching entirely — every task takes the scheduled path.
    lease_idle_timeout_s: float = 10.0
    # Max cached leases (distinct nodes) per scheduling key; spillback
    # grants beyond this replace the most-saturated lease instead.
    max_leases_per_key: int = 2
    # Local-scheduler queue depth on a leased node that triggers a
    # spillback re-grant (raylet spillback parity) when another node could
    # take the work.  1 = any resource queueing spills (evaluated at most
    # every 50ms per lease, so a throughput burst pays ~20 scheduling
    # decisions/s, not one per task). 0 disables spillback — leases only
    # rotate on expiry.
    lease_spillback_queue_depth: int = 1
    # Agent-side ObjectDirectory location commits coalesce into one
    # ``object_locations`` control RPC per batch: flush at this many
    # entries, or after the delay below — whichever comes first.
    location_commit_flush_count: int = 64
    location_commit_flush_delay_s: float = 0.003

    # ---- gray failures: deadlines, hedging, control-plane retries --------
    # End-to-end task deadlines (.options(deadline_s=...)): after the
    # deadline fires the task is cancelled cooperatively; if it has not
    # committed a terminal state within this grace window the hosting
    # worker is force-killed (CancelTask force_kill parity).
    task_deadline_grace_s: float = 2.0
    # Poll period of the owner-side watchdog that enforces deadlines and
    # fires hedged retries.  Deadline/hedge latency is bounded by one tick.
    watchdog_poll_period_s: float = 0.02
    # Opt-in automatic hedging: when enabled, dep-free retryable tasks of a
    # SchedulingKey with a settled latency EWMA hedge once their attempt
    # outlives ewma * hedge_auto_multiplier (never below hedge_auto_min_s).
    hedge_auto_enabled: bool = False
    hedge_auto_multiplier: float = 3.0
    hedge_auto_min_samples: int = 10
    hedge_auto_min_s: float = 0.05
    # Control-plane retry helper (rpc.retry_with_backoff): base delay,
    # multiplier cap, and default attempt count for retriable control RPCs.
    rpc_retry_base_backoff_s: float = 0.05
    rpc_retry_max_backoff_s: float = 2.0
    rpc_retry_max_attempts: int = 3

    # ---- overload survival: admission control + load shedding (ISSUE 9) --
    # Every waiting list between the ingress and the object store is
    # bounded; offered load beyond a bound SHEDS with a typed
    # OverloadedError carrying retry_after_s instead of growing a queue
    # until something OOMs.  See docs/fault_tolerance.md "Overload &
    # backpressure".
    #
    # Bound on the scheduler's parked demand queue (currently-infeasible
    # tasks/actor creations waiting for capacity).  Parks beyond it shed.
    demand_queue_max_entries: int = 4096
    # Per-caller cap on in-flight (submitted, not yet terminal) normal
    # tasks.  0 disables.  At the cap, submission follows
    # task_submit_overload_policy: "block" waits (bounded by
    # task_submit_block_timeout_s and the caller's remaining deadline
    # budget) then sheds; "shed" rejects immediately.
    max_inflight_tasks_per_caller: int = 0
    task_submit_overload_policy: str = "block"
    task_submit_block_timeout_s: float = 30.0
    # Bounded spill tier: max bytes of disk the object store's spill tier
    # may hold.  0 = unbounded (the pre-ISSUE-9 behavior).  When bounded, a
    # put that cannot fit in host + disk budgets backpressures up to
    # store_put_backpressure_timeout_s for deletions to free room, then
    # raises a typed StoreFullError (it never half-commits).
    object_store_max_disk_bytes: int = 0
    store_put_backpressure_timeout_s: float = 5.0
    # Default retry-after hint stamped on OverloadedError when a layer has
    # no better estimate of when capacity frees up.
    overload_retry_after_s: float = 1.0
    # Max seconds a request may WAIT in the serve router's bounded queue
    # (max_queued_requests >= 0) for a replica slot before shedding — a
    # wedged replica must cost a typed 429, not a handle call that never
    # returns.
    router_queue_wait_timeout_s: float = 30.0

    # ---- LLM serving engine: paged KV + chunked prefill -------------------
    # KV cache layout of serve.llm.LLMEngine: "paged" (default) allocates
    # fixed-size HBM pages per request through a block table — capacity
    # proportional to tokens actually cached; "dense" preallocates one
    # [L, B, Hkv, max_len, Dh] buffer (one full-length row per slot).
    # Engines under a mesh auto-fall back to dense (GSPMD paged scatter is
    # not wired yet).  See docs/tpu_design.md "Paged KV + chunked prefill".
    llm_cache_kind: str = "paged"
    # Tokens per KV page.  Smaller = finer-grained allocation (less slack
    # per request), larger = fewer pages to stream per decode step.  On
    # real TPUs keep it a multiple of the sublane tile (8 for f32, 16 for
    # bf16) so Pallas page blocks stay tileable.
    kv_block_size: int = 16
    # Total pages in the pool (one is reserved as the garbage page).
    # 0 = auto: max_batch_size * ceil(max_seq_len / kv_block_size) + 1,
    # i.e. dense-equivalent capacity; set it LOWER than auto to serve more
    # slots than dense could back at the same HBM budget.
    kv_num_blocks: int = 0
    # Chunked prefill (Sarathi-style bounded per-iteration prefill budget):
    # prompts longer than this prefill in fixed-size chunks interleaved
    # between decode steps, so running decodes never stall more than one
    # chunk's forward.  0 = one-shot (whole prompt, power-of-2 bucketed).
    prefill_chunk_tokens: int = 0
    # Prefix-aware KV reuse (paged engines only): finished requests publish
    # the full blocks of prompt+completion into a radix prefix cache
    # (serve/prefix_cache.py) and new requests share() the longest cached
    # prefix straight into their block table — zero prefill compute for the
    # hit region, refcounted pages, copy-on-write on divergence.
    llm_prefix_cache: bool = True
    # Max full blocks the prefix cache may pin (0 = bounded only by the
    # pool).  When the pool runs short, unreferenced cached leaves are
    # LRU-evicted before admission holds or sheds either way.
    prefix_cache_max_blocks: int = 0
    # Disaggregated prefill/decode serving (serve/disagg.py): attempts per
    # request on the migration fallback ladder.  Attempt 1 migrates the
    # prefill replica's KV blocks to a decode replica (device pull, then
    # host-staged fallback); each later attempt re-prefills from scratch on
    # a fresh prefill/decode pair.  Exhausting the ladder raises the typed
    # KVMigrationError to the caller.
    kv_migration_attempts: int = 2
    # Seconds the decode side waits for one staged KV block to arrive over
    # the device plane before treating the pull as refused and dropping to
    # the host-staged rung.
    kv_migration_pull_timeout_s: float = 30.0

    # ---- elastic gang-scheduled training (train/controller.py) -----------
    # Steps between TrainController step checkpoints (optimizer/step/RNG
    # state, digest-framed).  Repair-and-resume restores from the latest
    # one, so the period bounds recomputed work after a gang-member death.
    train_checkpoint_period_steps: int = 10
    # Floor on gang size: shrink recovery (a permanently-dead or preempted
    # member) and elastic resize never drop the gang below this many
    # members; below it the typed failure surfaces to the caller instead.
    train_gang_min_members: int = 1
    # Register the training gang with the admission machinery as a
    # preemptible background tenant: a serving burst may preempt members
    # (checkpoint -> shrink -> continue) and training absorbs spare
    # capacity.  Off = the gang holds its members like any foreground job.
    train_preemptible: bool = False

    # ---- request-scope serving observability -----------------------------
    # Lifecycle traces for serve requests (observability/reqtrace.py): a
    # RequestTrace born at the HTTP proxy rides the request through
    # router -> replica -> engine collecting phase-attributed timestamps,
    # kept in bounded rings and served by GET /api/requests + `rt requests`.
    # Pure wall-clock bookkeeping: consumes zero failpoint decisions, so
    # same-seed chaos fault logs stay byte-identical on or off.
    serve_request_trace: bool = True
    # Trace 1-in-N proxy requests (1 = every request).  Sampling bounds the
    # per-request overhead at high QPS; engine-side SLO sketches (TTFT,
    # inter-token) are unaffected — they observe every request regardless.
    serve_request_trace_sample_n: int = 1
    # Completed-trace ring capacity (recent + the slowest-N derive from it).
    serve_request_trace_ring: int = 512

    def apply_env_overrides(self) -> "Config":
        for f in dataclasses.fields(self):
            env_key = _ENV_PREFIX + f.name.upper()
            if env_key in os.environ:
                raw = os.environ[env_key]
                setattr(self, f.name, _coerce(raw, f.type))
        return self

    def apply_dict(self, overrides: Dict[str, Any]) -> "Config":
        for key, value in overrides.items():
            if not hasattr(self, key):
                raise ValueError(f"Unknown config key: {key}")
            setattr(self, key, value)
        return self


def _coerce(raw: str, annot: Any) -> Any:
    annot = str(annot)
    if "bool" in annot:
        return raw.lower() in ("1", "true", "yes")
    if "int" in annot:
        return int(raw)
    if "float" in annot:
        return float(raw)
    if "str" in annot:
        return raw
    return json.loads(raw)


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config().apply_env_overrides()
    return _global_config


def set_config(config: Config) -> None:
    global _global_config
    _global_config = config


def reset_config() -> None:
    global _global_config
    _global_config = None
