"""Streaming generator tasks: ObjectRefGenerator.

Parity: the reference's streaming-generator machinery
(``src/ray/core_worker/core_worker.h:389`` ``TryReadObjectRefStream``,
``python/ray/_raylet.pyx:273`` ``ObjectRefGenerator``; used by Data's
streaming executor and Serve's response streaming). A task whose function
is a generator and whose ``num_returns="streaming"`` returns ONE
``ObjectRefGenerator``; each yielded item commits to the object store as
its own return object the moment it is produced, and the caller iterates
ObjectRefs without waiting for the task to finish.

Error semantics (reference parity): an exception inside the generator
commits as the NEXT item (an errored ref — ``rt.get`` raises), then the
stream ends.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from ray_tpu.core.object_ref import ObjectRef


class ObjectRefGenerator:
    """Iterator over a streaming task's return refs, in yield order.

    Thread-safe: the executing node pushes refs as items commit; the
    consuming thread blocks in ``__next__`` until an item arrives or the
    stream finishes."""

    def __init__(self, task_id):
        self._task_id = task_id
        self._cond = threading.Condition()
        self._items: List[ObjectRef] = []
        self._read = 0
        self._done = False

    # -- producer side (runtime-internal) -----------------------------------
    def _push(self, ref: ObjectRef) -> None:
        with self._cond:
            self._items.append(ref)
            self._cond.notify_all()

    def _finish(self) -> None:
        with self._cond:
            self._done = True
            self._cond.notify_all()

    # -- consumer side ------------------------------------------------------
    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        with self._cond:
            while self._read >= len(self._items) and not self._done:
                self._cond.wait()
            if self._read < len(self._items):
                ref = self._items[self._read]
                self._read += 1
                return ref
            raise StopIteration

    def next_ready(self, timeout: Optional[float] = None) -> Optional[ObjectRef]:
        """Like ``next()`` but returns None on timeout instead of blocking
        forever; raises StopIteration when the stream is exhausted."""
        with self._cond:
            if self._read >= len(self._items) and not self._done:
                self._cond.wait(timeout)
            if self._read < len(self._items):
                ref = self._items[self._read]
                self._read += 1
                return ref
            if self._done:
                raise StopIteration
            return None

    @property
    def task_id(self):
        return self._task_id

    def num_ready(self) -> int:
        """Items produced but not yet consumed."""
        with self._cond:
            return len(self._items) - self._read

    def is_finished(self) -> bool:
        with self._cond:
            return self._done and self._read >= len(self._items)

    def __repr__(self) -> str:
        with self._cond:
            state = "done" if self._done else "open"
            return f"ObjectRefGenerator({self._task_id.hex()[:8]}, {len(self._items)} items, {state})"
