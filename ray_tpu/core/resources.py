"""Resource accounting with fixed-point arithmetic.

Parity with the reference (``src/ray/common/scheduling/fixed_point.h`` and
``cluster_resource_data.h:36``): resource quantities are stored as integer
milli-units so fractional requests (e.g. ``num_cpus=0.5``) never accumulate
floating-point drift.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping

SCALE = 1000  # milli-units


def to_fixed(value: float) -> int:
    return round(value * SCALE)


def from_fixed(value: int) -> float:
    return value / SCALE


class ResourceSet:
    """A bag of named resource quantities in fixed-point units."""

    __slots__ = ("_r",)

    def __init__(self, resources: Mapping[str, float] | None = None, *, _fixed: Dict[str, int] | None = None):
        if _fixed is not None:
            self._r = _fixed
        else:
            self._r = {k: to_fixed(v) for k, v in (resources or {}).items() if v != 0}

    @classmethod
    def from_fixed_dict(cls, fixed: Dict[str, int]) -> "ResourceSet":
        return cls(_fixed={k: v for k, v in fixed.items() if v != 0})

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._r.items()}

    def fixed(self) -> Dict[str, int]:
        return dict(self._r)

    def get(self, name: str) -> float:
        return from_fixed(self._r.get(name, 0))

    def is_empty(self) -> bool:
        return not self._r

    def names(self) -> Iterable[str]:
        return self._r.keys()

    # -- arithmetic --------------------------------------------------------
    def fits(self, available: "ResourceSet") -> bool:
        return all(available._r.get(k, 0) >= v for k, v in self._r.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._r)
        for k, v in other._r.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet.from_fixed_dict(out)

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._r)
        for k, v in other._r.items():
            out[k] = out.get(k, 0) - v
        return ResourceSet.from_fixed_dict(out)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._r == other._r

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"


class ResourcePool:
    """Total/available pair with acquire/release (LocalResourceManager parity,
    src/ray/raylet/scheduling/local_resource_manager.h:54).

    Internally locked: callers reach this pool from scheduler threads, task
    completion callbacks, and actor-creation retry threads concurrently.
    """

    def __init__(self, total: Mapping[str, float]):
        import threading

        self._lock = threading.Lock()
        self.total = ResourceSet(total)
        self._available = dict(self.total.fixed())

    @property
    def available(self) -> ResourceSet:
        with self._lock:
            return ResourceSet.from_fixed_dict(dict(self._available))

    def can_acquire(self, request: ResourceSet) -> bool:
        with self._lock:
            return all(self._available.get(k, 0) >= v for k, v in request.fixed().items())

    def acquire(self, request: ResourceSet) -> bool:
        req = request.fixed()
        with self._lock:
            if not all(self._available.get(k, 0) >= v for k, v in req.items()):
                return False
            for k, v in req.items():
                self._available[k] = self._available.get(k, 0) - v
            return True

    def release(self, request: ResourceSet) -> None:
        with self._lock:
            for k, v in request.fixed().items():
                total_k = self.total.fixed().get(k, 0)
                self._available[k] = min(self._available.get(k, 0) + v, total_k) if total_k else self._available.get(k, 0) + v

    def force_acquire(self, request: ResourceSet) -> None:
        """Deduct unconditionally (may go transiently negative).  Used when
        applying a head-authorized acquire on an agent's authoritative pool:
        the placement decision was already made against the head's view, so
        the agent must reflect it even mid-reconciliation."""
        with self._lock:
            for k, v in request.fixed().items():
                self._available[k] = self._available.get(k, 0) - v

    def add_capacity(self, extra: ResourceSet) -> None:
        """Grow the pool (used by placement-group bundle commit/return)."""
        with self._lock:
            self.total = self.total + extra
            for k, v in extra.fixed().items():
                self._available[k] = self._available.get(k, 0) + v

    def remove_capacity(self, extra: ResourceSet) -> None:
        with self._lock:
            self.total = self.total - extra
            for k, v in extra.fixed().items():
                self._available[k] = self._available.get(k, 0) - v

    def utilization(self) -> float:
        """Max utilization across dimensions (for the hybrid policy score)."""
        with self._lock:
            util = 0.0
            for k, total in self.total.fixed().items():
                if total <= 0:
                    continue
                used = total - self._available.get(k, 0)
                util = max(util, used / total)
            return util
