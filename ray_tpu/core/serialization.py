"""Serialization for cross-process object transport.

Parity with the reference's ``python/ray/_private/serialization.py``
(``SerializationContext``): a small metadata envelope plus pickle protocol 5
out-of-band buffers, so numpy arrays (and host-materialized ``jax.Array``s)
move between processes without an extra copy.  ObjectRefs pickled inside task
arguments are recorded by the context so the receiver can be registered as a
borrower (reference: ``serialization.py:145`` object_ref_reducer →
``ReferenceCounter`` borrower protocol).

TPU-first deltas from the reference:
  * In-process tasks (device tasks on the host runtime) never serialize at
    all — objects pass by reference.  This module is only used at process
    boundaries (CPU worker pool, multi-host transfer) and for spill tiers.
  * ``jax.Array`` serializes as (dtype, shape, sharding-less host bytes); on
    deserialize it becomes numpy, and re-materializes to HBM lazily on first
    device use.  Device-to-device movement across hosts rides ICI/DCN via the
    transfer layer, not this path.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Callable, List, Tuple

import numpy as np

_JAX_ARRAY_MARKER = b"__ray_tpu_jax_array__"


class SerializedObject:
    """Envelope: a pickle5 stream plus its out-of-band buffers."""

    __slots__ = ("meta", "buffers")

    def __init__(self, meta: bytes, buffers: List[pickle.PickleBuffer]):
        self.meta = meta
        self.buffers = buffers

    def total_bytes(self) -> int:
        return len(self.meta) + sum(b.raw().nbytes for b in self.buffers)

    def to_flat_parts(self) -> List[bytes]:
        """Flatten for socket/shm transport: [meta, buf0, buf1, ...]."""
        return [self.meta] + [bytes(b.raw()) for b in self.buffers]


class SerializationContext:
    """Pickle-5-based serializer with pluggable custom reducers.

    Thread-local hook state lets the object-ref reducer capture which refs are
    being smuggled inside an object graph (→ borrower registration).
    """

    def __init__(self):
        self._reducers: dict[type, Callable] = {}
        self._local = threading.local()

    def register_reducer(self, cls: type, reducer: Callable) -> None:
        self._reducers[cls] = reducer

    # -- ref capture hooks -------------------------------------------------
    def start_capture_refs(self) -> None:
        self._local.captured_refs = []

    def stop_capture_refs(self) -> list:
        refs = getattr(self._local, "captured_refs", [])
        self._local.captured_refs = None
        return refs

    def note_ref(self, ref) -> None:
        captured = getattr(self._local, "captured_refs", None)
        if captured is not None:
            captured.append(ref)

    # -- serialize/deserialize --------------------------------------------
    def serialize(self, value: Any) -> SerializedObject:
        buffers: List[pickle.PickleBuffer] = []

        class _Pickler(pickle.Pickler):
            dispatch_table = {}

            def reducer_override(p_self, obj):  # noqa: N805
                r = self._reducers.get(type(obj))
                if r is not None:
                    return r(obj)
                if isinstance(obj, np.ndarray) and obj.dtype != object:
                    return NotImplemented  # numpy handles PickleBuffer itself
                if _is_jax_array(obj):
                    host = np.asarray(obj)
                    return (_rebuild_jax_array, (host,))
                return NotImplemented

        import io

        stream = io.BytesIO()
        pickler = _Pickler(stream, protocol=5, buffer_callback=buffers.append)
        pickler.dump(value)
        return SerializedObject(stream.getvalue(), buffers)

    def deserialize(self, serialized: SerializedObject) -> Any:
        return pickle.loads(serialized.meta, buffers=serialized.buffers)

    def deserialize_parts(self, parts: List[bytes]) -> Any:
        meta, raw_bufs = parts[0], parts[1:]
        return pickle.loads(meta, buffers=[pickle.PickleBuffer(b) for b in raw_bufs])


def _is_jax_array(obj: Any) -> bool:
    # Avoid importing jax at module load for CPU-only worker processes.
    cls = type(obj)
    mod = cls.__module__ or ""
    return mod.startswith("jax") and cls.__name__ in ("ArrayImpl", "Array")


def _rebuild_jax_array(host: np.ndarray):
    # Deserialized jax arrays come back as numpy; they re-enter HBM lazily on
    # first device use (jit will device_put them).  This keeps worker-pool
    # processes free of device state.
    return host


_default_context: SerializationContext | None = None
_default_lock = threading.Lock()


def get_context() -> SerializationContext:
    global _default_context
    if _default_context is None:
        with _default_lock:
            if _default_context is None:
                _default_context = SerializationContext()
    return _default_context
