"""Unique identifiers for jobs, tasks, actors, objects, nodes and placement groups.

Design parity with the reference's ID scheme (``src/ray/common/id.h``): every ID is
a fixed-width byte string; ObjectIDs embed the TaskID that created them plus an
index, so lineage can be recovered from the ID itself.  TaskIDs embed the ActorID
(or a nil actor) and the JobID.  Unlike the reference we keep IDs as immutable
Python objects with interned bytes — there is no C++ struct to mirror because the
single-host runtime is one process and IDs never cross a language boundary.

Layout (sizes in bytes):
  JobID:    4
  ActorID:  12  = 8 unique + JobID
  TaskID:   20  = 8 unique (atomic counter) + ActorID
  ObjectID: 24  = TaskID + 4 (little-endian object index)
  NodeID:   16  random
  PlacementGroupID: 16 = 12 unique + JobID
  WorkerID: 16  random
"""

from __future__ import annotations

import itertools
import os
import threading

_JOB_ID_SIZE = 4
_ACTOR_UNIQUE_SIZE = 8
_ACTOR_ID_SIZE = _ACTOR_UNIQUE_SIZE + _JOB_ID_SIZE          # 12
_TASK_UNIQUE_SIZE = 8
_TASK_ID_SIZE = _TASK_UNIQUE_SIZE + _ACTOR_ID_SIZE          # 20
_OBJECT_INDEX_SIZE = 4
_OBJECT_ID_SIZE = _TASK_ID_SIZE + _OBJECT_INDEX_SIZE        # 24
_NODE_ID_SIZE = 16
_PG_UNIQUE_SIZE = 12
_PG_ID_SIZE = _PG_UNIQUE_SIZE + _JOB_ID_SIZE                # 16
_WORKER_ID_SIZE = 16


class BaseID:
    """Fixed-width binary identifier. Immutable, hashable, ordered."""

    SIZE = 0
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(_JOB_ID_SIZE, "little"))

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls.from_int(cls._counter)

    @classmethod
    def ensure_above(cls, value: int) -> None:
        """Advance the counter past ids restored from a previous process,
        so new jobs can't collide with persisted history."""
        with cls._lock:
            cls._counter = max(cls._counter, value)

    def int_value(self) -> int:
        return int.from_bytes(self._bytes, "little")


class NodeID(BaseID):
    SIZE = _NODE_ID_SIZE


class WorkerID(BaseID):
    SIZE = _WORKER_ID_SIZE


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(_ACTOR_UNIQUE_SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[_ACTOR_UNIQUE_SIZE:])


# Hot path: task ids are minted at submission rate; a process-wide atomic
# 64-bit counter is ~50x cheaper than urandom.  The counter starts at a
# RANDOM 62-bit offset: worker processes mint task ids locally
# (fire-and-forget nested submission), and two processes counting from a
# fixed base would collide on their early ids — observed as one task's
# return object satisfying another task's get.
_task_counter = itertools.count(int.from_bytes(os.urandom(8), "little") >> 2)
_UNIQUE_MASK = (1 << (8 * _TASK_UNIQUE_SIZE)) - 1


def _next_unique() -> bytes:
    return (next(_task_counter) & _UNIQUE_MASK).to_bytes(_TASK_UNIQUE_SIZE, "little")


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(_next_unique() + ActorID.nil().binary()[: _ACTOR_UNIQUE_SIZE] + job_id.binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_next_unique() + actor_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        # Deterministic: zero unique prefix marks the creation task.
        return cls(b"\x00" * _TASK_UNIQUE_SIZE + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        # 0xFE-filled prefix: the 8-byte little-endian counter reaches it
        # only after ~1.8e19 submissions
        return cls(b"\xfe" * _TASK_UNIQUE_SIZE + ActorID.nil().binary()[: _ACTOR_UNIQUE_SIZE] + job_id.binary())

    def actor_id(self) -> ActorID:
        embedded = self._bytes[_TASK_UNIQUE_SIZE:]
        # Normal tasks embed a nil actor-unique prefix (job id still present).
        if embedded[:_ACTOR_UNIQUE_SIZE] == b"\xff" * _ACTOR_UNIQUE_SIZE:
            return ActorID.nil()
        return ActorID(embedded)

    def job_id(self) -> JobID:
        return JobID(self._bytes[-_JOB_ID_SIZE:])


class ObjectID(BaseID):
    """Embeds the creating TaskID + return/put index → lineage is recoverable."""

    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        # index 0 is reserved for puts; returns start at 1 (reference convention).
        return cls(task_id.binary() + index.to_bytes(_OBJECT_INDEX_SIZE, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # puts use the high bit of the index to avoid collision with returns.
        idx = put_index | 0x80000000
        return cls(task_id.binary() + idx.to_bytes(_OBJECT_INDEX_SIZE, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_TASK_ID_SIZE])

    def job_id(self) -> JobID:
        return self.task_id().job_id()

    def index(self) -> int:
        return int.from_bytes(self._bytes[_TASK_ID_SIZE:], "little")

    def is_put(self) -> bool:
        return bool(self.index() & 0x80000000)

    def is_return(self) -> bool:
        return not self.is_put()


class PlacementGroupID(BaseID):
    SIZE = _PG_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(os.urandom(_PG_UNIQUE_SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[_PG_UNIQUE_SIZE:])


class UniqueID(BaseID):
    """General-purpose 28-byte id (reference ``kUniqueIDSize=28``,
    src/ray/common/id.h) — the base width of ids that don't embed lineage."""

    SIZE = 28


class FunctionID(UniqueID):
    """Identifies a registered function (content hash width parity:
    ``FunctionID``, src/ray/common/id.h)."""


class ActorClassID(UniqueID):
    """Identifies an exported actor class (``ActorClassID``,
    src/ray/common/id.h)."""


# --------------------------------------------------------------------------
# Native tier: the C extension re-implements these types with C-speed
# tp_hash/tp_richcompare (ids are the dict keys on every submit/result
# path).  Semantics are identical — tests/test_native_ids.py asserts parity
# class by class, and RAY_TPU_PURE_PY_IDS=1 keeps the Python classes (used
# by the parity tests themselves, and as the fallback wherever the
# toolchain can't build the extension).  All-or-nothing per process: mixing
# C and Python id instances in one dict would break equality.
if os.environ.get("RAY_TPU_PURE_PY_IDS") != "1":
    try:
        from ray_tpu.native import hotpath as _hotpath

        JobID = _hotpath.JobID  # noqa: F811
        NodeID = _hotpath.NodeID  # noqa: F811
        WorkerID = _hotpath.WorkerID  # noqa: F811
        ActorID = _hotpath.ActorID  # noqa: F811
        TaskID = _hotpath.TaskID  # noqa: F811
        ObjectID = _hotpath.ObjectID  # noqa: F811
        PlacementGroupID = _hotpath.PlacementGroupID  # noqa: F811
    except Exception:  # noqa: BLE001 — no compiler / load failure: Python tier
        pass
