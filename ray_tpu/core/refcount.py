"""Distributed reference counting with owner/borrower semantics.

Parity with the reference's ``ReferenceCounter``
(``src/ray/core_worker/reference_count.h:61``): every object has one owner
(the worker that created it).  The owner tracks, per object:

  * local references   — live ObjectRef handles in the owner process,
  * submitted-task refs — the object is an argument of an in-flight task,
  * borrowers          — remote workers holding refs (``reference_count.h:265``),
  * lineage refs       — downstream objects whose reconstruction would need
    this object (kept while lineage pinning is on).

When all counts reach zero the object is freed everywhere; if lineage is still
referenced the entry is kept so a lost object can be rebuilt by re-executing
its creating task (``task_manager.h:261``).

This is plain Python guarded by one lock: counts are touched a handful of
times per task, so the cost is noise compared to dispatch; the reference
needed C++ here because N processes share each count, whereas our single-host
runtime owns all counts in-process and multi-host borrowing goes through the
control plane.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

from ray_tpu.core.ids import ObjectID


class Reference:
    __slots__ = (
        "local_refs",
        "submitted_task_refs",
        "borrowers",
        "lineage_refs",
        "owned",
        "pinned",
        "on_delete",
    )

    def __init__(self, owned: bool = True):
        self.local_refs = 0
        self.submitted_task_refs = 0
        self.borrowers: Set[str] = set()
        self.lineage_refs = 0
        self.owned = owned
        self.pinned = False  # pinned objects are never freed (e.g. actor state)
        self.on_delete: Optional[Callable[[], None]] = None

    def total(self) -> int:
        return self.local_refs + self.submitted_task_refs + len(self.borrowers)

    def out_of_scope(self) -> bool:
        return self.total() == 0 and not self.pinned


class ReferenceCounter:
    def __init__(self, on_object_out_of_scope: Optional[Callable[[ObjectID], None]] = None):
        self._lock = threading.RLock()
        self._refs: Dict[ObjectID, Reference] = {}
        self._on_out_of_scope = on_object_out_of_scope
        # Deferred decrement queue: ObjectRef.__del__ may fire from GC while
        # ANY runtime lock is held, so it must never touch locks itself —
        # it enqueues here and a drainer thread applies the decrement.
        from collections import deque

        self._deferred: "deque[ObjectID]" = deque()
        self._deferred_event = threading.Event()
        self._drainer_stop = False
        self._drainer = threading.Thread(target=self._drain_loop, name="refcount-gc", daemon=True)
        self._drainer.start()

    def enqueue_local_ref_removal(self, object_id: ObjectID) -> None:
        """GC-safe: called from __del__; lock-free append + event set."""
        self._deferred.append(object_id)
        self._deferred_event.set()

    def _apply_pending(self) -> int:
        """Apply every queued __del__ decrement; returns how many."""
        n = 0
        while True:
            try:
                oid = self._deferred.popleft()
            except IndexError:
                return n
            try:
                self.remove_local_reference(oid)
                n += 1
            except Exception:  # noqa: BLE001
                pass

    def _drain_loop(self) -> None:
        while not self._drainer_stop:
            self._deferred_event.wait(timeout=0.5)
            self._deferred_event.clear()
            self._apply_pending()

    def stop(self) -> None:
        self._drainer_stop = True
        self._deferred_event.set()

    def drain_deferred(self) -> int:
        """Synchronously apply queued __del__ decrements (memory-pressure
        path: the store calls this before spilling so dead objects FREE
        instead of paying a spill copy).  A full gc.collect only runs when
        the queue was empty (cycles may still hold refs) and at most once
        per second — a legitimately-over-budget workload must not pay a
        stop-the-world GC per put."""
        n = self._apply_pending()
        if n == 0:
            import gc
            import time as _time

            now = _time.monotonic()
            if now - getattr(self, "_last_pressure_gc", 0.0) < 1.0:
                return 0
            self._last_pressure_gc = now
            gc.collect()
            n = self._apply_pending()
        return n

    # -- ownership --------------------------------------------------------
    def add_owned_object(self, object_id: ObjectID, pinned: bool = False) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                ref = Reference(owned=True)
                self._refs[object_id] = ref
            ref.pinned = ref.pinned or pinned

    def add_borrowed_object(self, object_id: ObjectID) -> None:
        with self._lock:
            if object_id not in self._refs:
                self._refs[object_id] = Reference(owned=False)

    # -- local refs -------------------------------------------------------
    def add_local_reference(self, object_id: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                ref = Reference(owned=True)
                self._refs[object_id] = ref
            ref.local_refs += 1

    def remove_local_reference(self, object_id: ObjectID) -> None:
        self._decrement(object_id, "local_refs")

    # -- task argument refs ------------------------------------------------
    def add_submitted_task_references(self, object_ids) -> None:
        with self._lock:
            for oid in object_ids:
                ref = self._refs.get(oid)
                if ref is None:
                    ref = Reference(owned=True)
                    self._refs[oid] = ref
                ref.submitted_task_refs += 1

    def remove_submitted_task_references(self, object_ids) -> None:
        for oid in object_ids:
            self._decrement(oid, "submitted_task_refs")

    # -- borrowers ---------------------------------------------------------
    def add_borrower(self, object_id: ObjectID, borrower: str) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.borrowers.add(borrower)

    def remove_borrower(self, object_id: ObjectID, borrower: str) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.borrowers.discard(borrower)
            if ref.out_of_scope():
                self._delete(object_id, ref)

    # -- lineage -----------------------------------------------------------
    def add_lineage_reference(self, object_id: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.lineage_refs += 1

    def remove_lineage_reference(self, object_id: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None and ref.lineage_refs > 0:
                ref.lineage_refs -= 1

    # -- queries -----------------------------------------------------------
    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                ref = Reference(owned=True)
                self._refs[object_id] = ref
            ref.pinned = True

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.pinned = False
                if ref.out_of_scope():
                    self._delete(object_id, ref)

    def has_reference(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._refs

    def reference_counts(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return None
            return {
                "local": ref.local_refs,
                "submitted": ref.submitted_task_refs,
                "borrowers": len(ref.borrowers),
                "lineage": ref.lineage_refs,
                "pinned": ref.pinned,
            }

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    # -- internals ---------------------------------------------------------
    def _decrement(self, object_id: ObjectID, field: str) -> None:
        callback = None
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            current = getattr(ref, field)
            if current > 0:
                setattr(ref, field, current - 1)
            if ref.out_of_scope():
                callback = self._delete(object_id, ref, run_callback=False)
        if callback is not None:
            callback()

    def _delete(self, object_id: ObjectID, ref: Reference, run_callback: bool = True):
        del self._refs[object_id]
        on_delete = ref.on_delete

        def fire():
            if on_delete is not None:
                on_delete()
            if self._on_out_of_scope is not None:
                self._on_out_of_scope(object_id)

        if run_callback:
            fire()
            return None
        return fire
