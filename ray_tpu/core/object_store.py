"""Tiered object store: HBM-resident jax.Arrays with host/shm/disk spill.

This is the rebuild of the reference's two stores:

  * plasma (``src/ray/object_manager/plasma/store.h``) — node-wide shared
    immutable objects; here the **native shm tier** (``ray_tpu/native``) plus
    the host tier play that role.
  * the in-memory store (``src/ray/core_worker/store_provider/memory_store/
    memory_store.h:43``) — small/inline objects and errors with blocking Get;
    here every entry supports blocking get via a per-object future.

TPU-first: the *primary* tier is HBM — a ``jax.Array`` is stored as-is
(zero-copy; XLA async dispatch means a stored array may still be materializing
on device, which is invisible to the table).  Spill order under memory
pressure mirrors plasma's pinned→evictable flow
(``object_lifecycle_manager.h``): DEVICE → HOST (device_get), HOST → SHM
(large buffers, zero-copy for workers) or DISK (pickled), with LRU ordering.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
# py3.10: futures.TimeoutError is NOT the builtin (unified only in 3.11)
from concurrent.futures import TimeoutError as _FutureTimeoutError
from enum import Enum
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError, StoreFullError
from ray_tpu.observability import metric_defs


class Tier(Enum):
    DEVICE = "device"   # jax.Array in HBM
    HOST = "host"       # any python object in process heap
    SHM = "shm"         # native shared-memory store (serialized)
    DISK = "disk"       # pickled file in spill_dir


def _is_device_array(value: Any) -> bool:
    cls = type(value)
    mod = cls.__module__ or ""
    if not mod.startswith("jax"):
        return False
    try:
        import jax

        return isinstance(value, jax.Array) and all(
            d.platform != "cpu" for d in value.devices()
        )
    except Exception:
        return False


def _nbytes(value: Any) -> int:
    if isinstance(value, np.ndarray):
        return value.nbytes
    nb = getattr(value, "nbytes", None)
    if isinstance(nb, int):
        return nb
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    return 0  # small control-plane object; not accounted


class ObjectEntry:
    __slots__ = ("value", "tier", "size", "is_error", "meta", "disk_path")

    def __init__(self, value: Any, tier: Tier, size: int, is_error: bool = False):
        self.value = value
        self.tier = tier
        self.size = size
        self.is_error = is_error
        self.meta: Optional[dict] = None
        self.disk_path: Optional[str] = None


class ObjectStore:
    """Single-host object table. Thread-safe; blocking gets via futures."""

    def __init__(self, shm_store=None, hbm_budget: Optional[int] = None, host_budget: Optional[int] = None):
        cfg = get_config()
        self._lock = threading.RLock()
        self._entries: "OrderedDict[ObjectID, ObjectEntry]" = OrderedDict()
        self._waiters: Dict[ObjectID, List[Future]] = {}
        self._shm = shm_store
        self._hbm_used = 0
        self._host_used = 0
        self._hbm_budget = hbm_budget if hbm_budget is not None else cfg.object_store_hbm_bytes or _auto_hbm_budget()
        self._host_budget = host_budget if host_budget is not None else cfg.object_store_host_bytes
        self._spill_dir = cfg.spill_dir
        # bounded spill tier (overload survival, ISSUE 9): bytes currently
        # spilled to disk, charged against object_store_max_disk_bytes when
        # that knob is set.  A put that cannot fit host + disk budgets
        # BACKPRESSURES on this condition (deletions notify it) up to
        # store_put_backpressure_timeout_s, then raises StoreFullError —
        # the spill tier never grows unbounded and never half-commits.
        self._disk_used = 0
        # bytes of gate-admitted puts not yet inserted: the admission check
        # must count them or N concurrent puts each seeing the last free
        # bytes would ALL pass and overshoot the budget N-fold
        self._pending_put_bytes = 0
        self._space = threading.Condition(self._lock)
        self.num_puts = 0
        self.num_gets = 0
        self.num_spills = 0
        self.num_restores = 0
        self.num_backpressure_waits = 0
        self.num_puts_shed = 0
        # per-node metric tag sets, prebuilt once (hot-path allocations);
        # the hosting Node calls set_metrics_tags with its node id
        self._tags: Optional[Dict[str, str]] = None
        self._tags_hbm: Dict[str, str] = {"tier": "hbm"}
        self._tags_host: Dict[str, str] = {"tier": "host"}
        self._tags_hit: Dict[str, str] = {"result": "hit"}
        self._tags_miss: Dict[str, str] = {"result": "miss"}

    def set_metrics_tags(self, tags: Dict[str, str]) -> None:
        self._tags = dict(tags)
        self._tags_hbm = {**tags, "tier": "hbm"}
        self._tags_host = {**tags, "tier": "host"}
        self._tags_hit = {**tags, "result": "hit"}
        self._tags_miss = {**tags, "result": "miss"}

    # ------------------------------------------------------------------ put
    def put(self, object_id: ObjectID, value: Any, is_error: bool = False) -> None:
        if _is_device_array(value):
            tier, size = Tier.DEVICE, _nbytes(value)
        else:
            tier, size = Tier.HOST, _nbytes(value)
        reserved = False
        if tier is Tier.HOST and size and not is_error:
            # error tombstones always commit (a failed task's error must
            # reach its getters even under memory pressure); data puts pay
            # the admission gate when the spill tier is bounded
            reserved = self._admit_put(object_id, size)
        entry = ObjectEntry(value, tier, size, is_error)
        with self._lock:
            if reserved:
                self._pending_put_bytes -= size  # reservation becomes the entry
            old = self._entries.get(object_id)
            if old is not None:
                # overwriting frees the old entry's footprint INCLUDING its
                # spill copy (the _admit_put gate already credited this
                # room) and wakes backpressured puts, exactly like delete()
                self._account_remove_locked(old)
                self._drop_spill_locked(object_id, old)
                self._space.notify_all()
            self._entries[object_id] = entry
            self._entries.move_to_end(object_id)
            if tier is Tier.DEVICE:
                self._hbm_used += size
            else:
                self._host_used += size
            self.num_puts += 1
            waiters = self._waiters.pop(object_id, [])
            n_entries = len(self._entries)
            tier_used = self._hbm_used if tier is Tier.DEVICE else self._host_used
        metric_defs.OBJECT_STORE_PUTS.inc(tags=self._tags)
        if size:
            metric_defs.OBJECT_STORE_BYTES_PUT.inc(size, tags=self._tags)
        metric_defs.OBJECT_STORE_OBJECTS.set(n_entries, self._tags)
        metric_defs.OBJECT_STORE_USED_BYTES.set(
            tier_used, self._tags_hbm if tier is Tier.DEVICE else self._tags_host
        )
        for fut in waiters:
            if not fut.done():
                fut.set_result(value)
        self._maybe_spill()

    def put_error(self, object_id: ObjectID, error: BaseException) -> None:
        self.put(object_id, error, is_error=True)

    def _admit_put(self, object_id: ObjectID, size: int) -> bool:
        """Backpressure gate for host-tier puts under a BOUNDED spill tier
        (``object_store_max_disk_bytes > 0``; 0 keeps the historical
        unbounded-spill behavior).  Blocks — waking on deletions — until
        the put fits within host + disk budgets, for at most
        ``store_put_backpressure_timeout_s``; then raises a typed
        :class:`StoreFullError` having committed nothing.  On success the
        size is RESERVED (``_pending_put_bytes``) until the entry inserts,
        so concurrent admits cannot all claim the same free bytes; returns
        True iff a reservation was taken."""
        cfg = get_config()
        disk_budget = cfg.object_store_max_disk_bytes
        if disk_budget <= 0:
            return False
        waited = 0.0
        deadline = None
        with self._lock:
            while True:
                # an overwrite frees the old entry's footprint in the same
                # commit; count that room as available
                old = self._entries.get(object_id)
                credit = (
                    old.size
                    if old is not None and old.tier in (Tier.HOST, Tier.DISK)
                    else 0
                )
                footprint = self._host_used + self._disk_used + self._pending_put_bytes - credit
                if footprint + size <= self._host_budget + disk_budget:
                    self._pending_put_bytes += size
                    break
                if deadline is None:
                    deadline = time.monotonic() + cfg.store_put_backpressure_timeout_s
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.num_puts_shed += 1
                    if waited:
                        metric_defs.STORE_PUT_BACKPRESSURE.observe(waited, tags=self._tags)
                    from ray_tpu.runtime.admission import record_shed

                    record_shed("store", "spill_full", task_id=object_id.hex())
                    raise StoreFullError(waited_s=waited, needed=size)
                if waited == 0.0:
                    self.num_backpressure_waits += 1  # one per blocked put
                t0 = time.monotonic()
                self._space.wait(min(remaining, 0.1))
                waited += time.monotonic() - t0
        if waited:
            metric_defs.STORE_PUT_BACKPRESSURE.observe(waited, tags=self._tags)
        return True

    # ------------------------------------------------------------------ get
    def get_async(self, object_id: ObjectID) -> Future:
        fut: Future = Future()
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None:
                value = self._materialize_locked(object_id, entry)
                self._entries.move_to_end(object_id)
                self.num_gets += 1
                size = entry.size
                fut.set_result(value)
                metric_defs.OBJECT_STORE_GETS.inc(tags=self._tags_hit)
                if size:
                    metric_defs.OBJECT_STORE_BYTES_GOT.inc(size, tags=self._tags)
                return fut
            self._waiters.setdefault(object_id, []).append(fut)
        metric_defs.OBJECT_STORE_GETS.inc(tags=self._tags_miss)
        return fut

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        fut = self.get_async(object_id)
        try:
            return fut.result(timeout)
        except (TimeoutError, _FutureTimeoutError):
            raise GetTimeoutError(f"Get timed out for {object_id}")

    def get_batch(self, object_ids: Sequence[ObjectID], timeout: Optional[float] = None) -> List[Any]:
        futures = [self.get_async(oid) for oid in object_ids]
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for fut in futures:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                out.append(fut.result(remaining))
            except (TimeoutError, _FutureTimeoutError):
                raise GetTimeoutError("Get timed out")
        return out

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._entries

    def is_ready(self, object_id: ObjectID) -> bool:
        return self.contains(object_id)

    def entry_info(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return None
            return {"tier": e.tier.value, "size": e.size, "is_error": e.is_error}

    def list_entries(self):
        """[(object_id, entry_info dict)] snapshot — the state API's
        GetObjectsInfo equivalent (node_manager.proto:426)."""
        with self._lock:
            return [
                (oid, {"tier": e.tier.value, "size": e.size, "is_error": e.is_error})
                for oid, e in self._entries.items()
            ]

    def _drop_spill_locked(self, object_id: ObjectID, entry: ObjectEntry) -> None:
        """Free an entry's spill copy (the ONE cleanup idiom for delete and
        overwrite): pinned SHM segments unpin+delete, DISK files come off
        the bounded-tier ledger and unlink."""
        if entry.tier is Tier.SHM and self._shm is not None:
            self._shm.unpin(object_id.binary())
            self._shm.delete(object_id.binary())
        elif entry.tier is Tier.DISK and entry.disk_path:
            self._disk_used -= entry.size
            try:
                os.unlink(entry.disk_path)
            except OSError:
                pass

    # --------------------------------------------------------------- delete
    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.pop(object_id, None)
            if entry is None:
                return
            self._account_remove_locked(entry)
            self._drop_spill_locked(object_id, entry)
            # room freed: wake puts blocked on the backpressure gate
            self._space.notify_all()

    def fail_pending(self, object_id: ObjectID, error: BaseException) -> None:
        """Wake waiters with an error without storing a value."""
        with self._lock:
            waiters = self._waiters.pop(object_id, [])
        for fut in waiters:
            if not fut.done():
                fut.set_exception(error)

    # ---------------------------------------------------------------- spill
    #: optional memory-pressure hook (wired by the runtime to the reference
    #: counter's synchronous drain): dead refs awaiting the GC drainer
    #: thread must FREE, not SPILL — plasma's evict-after-refcount ordering
    pressure_callback = None

    def _maybe_spill(self) -> None:
        with self._lock:
            over = (
                self._hbm_used > self._hbm_budget
                or self._host_used > self._host_budget
            )
        if over and self.pressure_callback is not None:
            try:
                # apply pending out-of-scope deletions before copying
                # anything out: a tight put loop outruns the deferred-decref
                # drainer on small hosts, and spilling already-dead objects
                # costs GB-scale memcpys for nothing
                self.pressure_callback()
            except Exception:  # noqa: BLE001 — pressure relief is best-effort
                pass
        with self._lock:
            if self._hbm_used > self._hbm_budget:
                self._spill_device_locked(self._hbm_used - self._hbm_budget)
            if self._host_used > self._host_budget:
                self._spill_host_locked(self._host_used - self._host_budget)

    def _spill_device_locked(self, need: int) -> None:
        freed = 0
        for oid, entry in list(self._entries.items()):
            if freed >= need:
                break
            if entry.tier is Tier.DEVICE:
                host = np.asarray(entry.value)  # device_get; sync point
                entry.value = host
                entry.tier = Tier.HOST
                self._hbm_used -= entry.size
                self._host_used += entry.size
                freed += entry.size
                self.num_spills += 1
                metric_defs.OBJECT_STORE_SPILLS.inc(tags=self._tags_host)

    def _spill_host_locked(self, need: int) -> None:
        freed = 0
        for oid, entry in list(self._entries.items()):
            if freed >= need:
                break
            if entry.tier is not Tier.HOST or entry.size == 0:
                continue
            if self._try_spill_entry_locked(oid, entry):
                freed += entry.size

    def _try_spill_entry_locked(self, oid: ObjectID, entry: ObjectEntry) -> bool:
        value = entry.value
        if self._shm is not None and isinstance(value, np.ndarray) and value.dtype != object:
            try:
                header = pickle.dumps((value.dtype.str, value.shape))
                data = np.ascontiguousarray(value)
                payload = header + data.tobytes()
                # pinned: the shm copy is the only copy, LRU must not evict it
                self._shm.put(oid.binary(), payload, meta_size=len(header), pin=True)
                entry.value = None
                entry.tier = Tier.SHM
                self._host_used -= entry.size
                self.num_spills += 1
                metric_defs.OBJECT_STORE_SPILLS.inc(tags=self._spill_tags("shm"))
                return True
            except (MemoryError, FileExistsError):
                pass
        # disk fallback — refused when the bounded spill tier has no room
        # (the put-side backpressure gate owns the full-store story; an
        # over-budget host just stays over until deletions land)
        disk_budget = get_config().object_store_max_disk_bytes
        if disk_budget > 0 and self._disk_used + entry.size > disk_budget:
            return False
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir, oid.hex())
        with open(path, "wb") as f:
            pickle.dump(value, f, protocol=5)
        entry.value = None
        entry.tier = Tier.DISK
        entry.disk_path = path
        self._host_used -= entry.size
        self._disk_used += entry.size
        self.num_spills += 1
        metric_defs.OBJECT_STORE_SPILLS.inc(tags=self._spill_tags("disk"))
        return True

    def _spill_tags(self, tier: str) -> Dict[str, str]:
        # spills are rare (memory-pressure only): building the tag dict
        # here is fine, unlike the per-put/get fast paths
        return {**(self._tags or {}), "tier": tier}

    def _materialize_locked(self, oid: ObjectID, entry: ObjectEntry) -> Any:
        if entry.tier in (Tier.DEVICE, Tier.HOST):
            return entry.value
        if entry.tier is Tier.SHM:
            got = self._shm.get(oid.binary())
            if got is None:
                raise ObjectLostError(oid)
            view, meta_size = got
            try:
                dtype_str, shape = pickle.loads(view[:meta_size])
                value = np.frombuffer(view[meta_size:], dtype=np.dtype(dtype_str)).reshape(shape).copy()
            finally:
                self._shm.release(oid.binary())
            entry.value = value
            entry.tier = Tier.HOST
            self._host_used += entry.size
            self._shm.unpin(oid.binary())  # drop the spill pin, then delete
            self._shm.delete(oid.binary())
            self.num_restores += 1
            metric_defs.OBJECT_STORE_RESTORES.inc(tags=self._tags)
            return value
        if entry.tier is Tier.DISK:
            with open(entry.disk_path, "rb") as f:
                value = pickle.load(f)
            entry.value = value
            entry.tier = Tier.HOST
            self._host_used += entry.size
            self._disk_used -= entry.size
            try:
                os.unlink(entry.disk_path)
            except OSError:
                pass
            entry.disk_path = None
            self.num_restores += 1
            metric_defs.OBJECT_STORE_RESTORES.inc(tags=self._tags)
            return value
        raise ObjectLostError(oid)

    def _account_remove_locked(self, entry: ObjectEntry) -> None:
        if entry.tier is Tier.DEVICE:
            self._hbm_used -= entry.size
        elif entry.tier is Tier.HOST:
            self._host_used -= entry.size

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._entries),
                "hbm_used": self._hbm_used,
                "hbm_budget": self._hbm_budget,
                "host_used": self._host_used,
                "host_budget": self._host_budget,
                "disk_used": self._disk_used,
                "disk_budget": get_config().object_store_max_disk_bytes,
                "puts": self.num_puts,
                "gets": self.num_gets,
                "spills": self.num_spills,
                "restores": self.num_restores,
                "put_backpressure_waits": self.num_backpressure_waits,
                "puts_shed": self.num_puts_shed,
            }


def _auto_hbm_budget() -> int:
    cfg = get_config()
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit * cfg.object_store_hbm_fraction)
    except Exception:
        pass
    return 4 * 1024**3
