"""Task bookkeeping: pending tasks, retries, lineage-based reconstruction.

Parity with the reference's ``TaskManager``
(``src/ray/core_worker/task_manager.h:208``): every submitted task is tracked
until its returns are committed; failed tasks retry up to ``max_retries``
(system failures always eligible; application errors only with
``retry_exceptions``); and the spec of each finished task is retained —
bounded by ``max_lineage_bytes`` parity via an entry cap — so a lost object
can be rebuilt by resubmitting its creating task
(``task_manager.h:261``, ``object_recovery_manager.h:41``).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.exceptions import ObjectReconstructionFailedError


class TaskManager:
    def __init__(self, max_lineage_entries: int = 100_000):
        self._lock = threading.RLock()
        self._pending: Dict[TaskID, object] = {}       # TaskSpec
        self._lineage: Dict[ObjectID, object] = {}     # return id -> TaskSpec
        self._lineage_order: list = []
        self._max_lineage = max_lineage_entries
        self.num_completed = 0
        self.num_failed = 0
        self.num_retries = 0

    # ------------------------------------------------------------------
    def add_pending(self, spec) -> None:
        with self._lock:
            self._pending[spec.task_id] = spec

    def mark_completed(self, spec) -> None:
        with self._lock:
            self._pending.pop(spec.task_id, None)
            self.num_completed += 1
            # retain lineage for reconstruction
            for oid in spec.return_ids:
                if oid not in self._lineage:
                    self._lineage_order.append(oid)
                self._lineage[oid] = spec
            while len(self._lineage_order) > self._max_lineage:
                old = self._lineage_order.pop(0)
                self._lineage.pop(old, None)

    def mark_failed(self, spec) -> None:
        with self._lock:
            self._pending.pop(spec.task_id, None)
            self.num_failed += 1

    def claim(self, spec) -> bool:
        """Atomically claim the right to commit ONE terminal state for the
        task: pops the pending entry, True only for the first claimant.
        Used by the racing deadline paths (watchdog direct-fail vs a
        straggler completion) — (task_id, attempt) terminal-exactly-once
        depends on exactly one of them winning.  Only valid for tasks that
        can no longer retry (a claimed task cannot re-enter pending)."""
        with self._lock:
            return self._pending.pop(spec.task_id, None) is not None

    def should_retry(self, spec, is_system_error: bool, retry_exceptions: bool = False) -> bool:
        if spec.retries_left <= 0:
            return False
        if not is_system_error and not retry_exceptions:
            return False
        with self._lock:
            spec.retries_left -= 1
            spec.attempt += 1
            self.num_retries += 1
        return True

    # ------------------------------------------------------------------
    def lineage_spec(self, object_id: ObjectID):
        with self._lock:
            return self._lineage.get(object_id)

    def get_pending(self, task_id: TaskID):
        """O(1) pending-spec lookup (ObjectIDs embed their creating TaskID,
        so ``ref -> spec`` needs no scan — reference: task id index in
        ``task_manager.h``)."""
        with self._lock:
            return self._pending.get(task_id)

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def pending_specs(self):
        with self._lock:
            return list(self._pending.values())


class ObjectRecoveryManager:
    """Rebuilds lost objects by re-executing their creating tasks
    (parity: src/ray/core_worker/object_recovery_manager.h:41)."""

    def __init__(self, task_manager: TaskManager, resubmit_fn: Callable[[object], None]):
        self._tm = task_manager
        self._resubmit = resubmit_fn
        self._lock = threading.Lock()
        self._recovering: set = set()

    def recover(self, object_id: ObjectID) -> bool:
        """Kick off reconstruction. Returns False if no lineage exists."""
        spec = self._tm.lineage_spec(object_id)
        if spec is None:
            return False
        with self._lock:
            if spec.task_id in self._recovering:
                return True
            self._recovering.add(spec.task_id)
        try:
            # Recursively recover missing dependencies first.
            self._resubmit(spec)
            return True
        finally:
            with self._lock:
                self._recovering.discard(spec.task_id)
