"""Job manager: runs submitted entrypoints as supervised subprocesses.

Parity with ``dashboard/modules/job/job_manager.py:56``: each submitted job
gets a supervisor that exec's the entrypoint shell command, captures its
output to a per-job log file in the session directory, tracks the status
FSM (PENDING → RUNNING → SUCCEEDED/FAILED/STOPPED), and applies the job's
``runtime_env`` (env_vars / working_dir) to the subprocess.  The reference's
supervisor is a detached actor; here a watcher thread per job suffices
because the manager lives in the head process.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
import uuid
from enum import Enum
from typing import Dict, List, Optional


class JobStatus(str, Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class _JobEntry:
    def __init__(self, submission_id: str, entrypoint: str, metadata: Optional[dict]):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.metadata = metadata or {}
        self.status = JobStatus.PENDING
        self.message = ""
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        self.proc: Optional[subprocess.Popen] = None
        self.log_path: Optional[str] = None
        self.env_uris: list = []

    def to_dict(self) -> dict:
        return {
            "submission_id": self.submission_id,
            "entrypoint": self.entrypoint,
            "status": self.status.value,
            "message": self.message,
            "metadata": self.metadata,
            "start_time": self.start_time,
            "end_time": self.end_time,
        }


class JobManager:
    def __init__(self, cluster):
        self.cluster = cluster
        self._lock = threading.Lock()
        self._jobs: Dict[str, _JobEntry] = {}
        self._log_dir = os.path.join(cluster.session_dir, "logs")
        os.makedirs(self._log_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def submit_job(
        self,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
        submission_id: Optional[str] = None,
    ) -> str:
        sub_id = submission_id or f"rtjob_{uuid.uuid4().hex[:16]}"
        with self._lock:
            if sub_id in self._jobs:
                raise ValueError(f"submission_id {sub_id!r} already exists")
            entry = _JobEntry(sub_id, entrypoint, metadata)
            self._jobs[sub_id] = entry

        env = dict(os.environ)
        env["RAY_TPU_SUBMISSION_ID"] = sub_id
        # Make the framework importable in the driver regardless of cwd.
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        cwd = None
        env_uris: list = []
        if runtime_env:
            from ray_tpu.runtime_env.plugin import apply_to_process_env, remove_references

            try:
                # plugins pin each staged artifact (refcount) as they stage it;
                # released in _watch when the process exits.
                env, cwd = apply_to_process_env(runtime_env, env, uris_out=env_uris)
                # command-wrapping plugins (mpi -> mpirun, container ->
                # podman/docker run) rewrite the entrypoint itself
                from ray_tpu.runtime_env.plugin import wrap_entrypoint

                entrypoint = wrap_entrypoint(runtime_env, entrypoint, env, cwd)
            except Exception as exc:
                with self._lock:
                    entry.status = JobStatus.FAILED
                    entry.message = f"runtime_env setup failed: {exc}"
                    entry.end_time = time.time()
                remove_references(env_uris)
                return sub_id
        entry.env_uris = env_uris

        with self._lock:
            if entry.status == JobStatus.STOPPED:  # stop raced env staging
                self._release_env(entry)
                return sub_id

        entry.log_path = os.path.join(self._log_dir, f"job-{sub_id}.log")
        log_file = open(entry.log_path, "wb")
        try:
            proc = subprocess.Popen(
                entrypoint,
                shell=True,
                stdout=log_file,
                stderr=subprocess.STDOUT,
                env=env,
                cwd=cwd,
                start_new_session=True,  # own process group so stop_job can kill the tree
            )
        except OSError as exc:
            entry.status = JobStatus.FAILED
            entry.message = f"failed to start: {exc}"
            entry.end_time = time.time()
            log_file.close()
            self._release_env(entry)
            return sub_id
        with self._lock:
            entry.proc = proc
            stopped_mid_start = entry.status == JobStatus.STOPPED
            if not stopped_mid_start:
                entry.status = JobStatus.RUNNING
        if stopped_mid_start:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        threading.Thread(
            target=self._watch, args=(entry, log_file), name=f"job-{sub_id}", daemon=True
        ).start()
        return sub_id

    def _release_env(self, entry: _JobEntry) -> None:
        if entry.env_uris:
            from ray_tpu.runtime_env.plugin import remove_references

            remove_references(entry.env_uris)
            entry.env_uris = []

    def _watch(self, entry: _JobEntry, log_file) -> None:
        code = entry.proc.wait()
        log_file.close()
        with self._lock:
            if entry.status == JobStatus.RUNNING:
                entry.status = JobStatus.SUCCEEDED if code == 0 else JobStatus.FAILED
                entry.message = f"exit code {code}"
            entry.end_time = time.time()
        self._release_env(entry)

    # ------------------------------------------------------------------
    def get_job(self, submission_id: str) -> Optional[dict]:
        with self._lock:
            entry = self._jobs.get(submission_id)
            return entry.to_dict() if entry else None

    def list_jobs(self) -> List[dict]:
        with self._lock:
            return [e.to_dict() for e in self._jobs.values()]

    def get_logs(self, submission_id: str) -> Optional[str]:
        with self._lock:
            entry = self._jobs.get(submission_id)
        if entry is None or entry.log_path is None:
            return None
        try:
            with open(entry.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def stop_job(self, submission_id: str) -> bool:
        with self._lock:
            entry = self._jobs.get(submission_id)
            if entry is None:
                return False
            if entry.status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return True
            # PENDING (still staging) or RUNNING: record the stop; the
            # submit path honors it if the process hasn't launched yet.
            entry.status = JobStatus.STOPPED
            entry.message = "stopped by user"
            proc = entry.proc
            if proc is None:
                entry.end_time = time.time()
                return True
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def wait_job(self, submission_id: str, timeout: float = 60.0) -> Optional[dict]:
        """Block until the job reaches a terminal state (test/CLI helper)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            info = self.get_job(submission_id)
            if info is None:
                return None
            if info["status"] in ("SUCCEEDED", "FAILED", "STOPPED"):
                return info
            time.sleep(0.05)
        return self.get_job(submission_id)

    def shutdown(self) -> None:
        with self._lock:
            entries = list(self._jobs.values())
        for e in entries:
            if e.status == JobStatus.RUNNING and e.proc is not None:
                try:
                    os.killpg(os.getpgid(e.proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
