"""Job submission: manager, supervisor processes, and the HTTP SDK.

Parity with the reference's ``dashboard/modules/job/``: ``JobManager``
(``job_manager.py:56``) drives one supervisor per submitted job;
``JobSubmissionClient`` (``sdk.py:39``) is the REST client; the CLI front
end is ``rt job submit/status/logs/stop/list``.
"""

from ray_tpu.job.manager import JobManager, JobStatus
from ray_tpu.job.models import DriverInfo, JobDetails, JobInfo, JobType
from ray_tpu.job.sdk import JobSubmissionClient

__all__ = [
    "JobManager",
    "JobStatus",
    "JobSubmissionClient",
    "JobInfo",
    "JobDetails",
    "JobType",
    "DriverInfo",
]
