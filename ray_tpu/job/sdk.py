"""JobSubmissionClient: HTTP client for the dashboard's job endpoints.

Parity with ``dashboard/modules/job/sdk.py:39`` (``submit_job`` :129) over
stdlib urllib — no requests dependency.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import List, Optional


class JobSubmissionClient:
    def __init__(self, address: str):
        """``address`` is the dashboard URL, e.g. ``http://127.0.0.1:8265``."""
        self.address = address.rstrip("/")

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.address + path,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            try:
                detail = json.loads(exc.read()).get("error", "")
            except Exception:
                detail = ""
            raise RuntimeError(f"{method} {path} -> {exc.code}: {detail}") from None

    # ------------------------------------------------------------------
    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
        submission_id: Optional[str] = None,
    ) -> str:
        body = {"entrypoint": entrypoint}
        if runtime_env:
            body["runtime_env"] = runtime_env
        if metadata:
            body["metadata"] = metadata
        if submission_id:
            body["submission_id"] = submission_id
        return self._request("POST", "/api/jobs/", body)["submission_id"]

    def get_job_info(self, submission_id: str) -> dict:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def get_job_logs(self, submission_id: str) -> str:
        return self._request("GET", f"/api/jobs/{submission_id}/logs")["logs"]

    def stop_job(self, submission_id: str) -> bool:
        try:
            return self._request("POST", f"/api/jobs/{submission_id}/stop")["stopped"]
        except RuntimeError as exc:
            if "-> 404" in str(exc):  # unknown submission id
                return False
            raise

    def list_jobs(self) -> List[dict]:
        return self._request("GET", "/api/jobs/")["jobs"]

    def wait_until_finished(self, submission_id: str, timeout: float = 120.0) -> dict:
        deadline = time.time() + timeout
        while time.time() < deadline:
            info = self.get_job_info(submission_id)
            if info["status"] in ("SUCCEEDED", "FAILED", "STOPPED"):
                return info
            time.sleep(0.2)
        raise TimeoutError(f"job {submission_id} still {self.get_job_status(submission_id)} after {timeout}s")
