"""Job data models (parity: ``python/ray/dashboard/modules/job/pydantic_models.py``
— JobDetails/JobType/DriverInfo — and ``common.py`` JobInfo)."""

from __future__ import annotations

import dataclasses
from enum import Enum
from typing import Any, Dict, Optional

from ray_tpu.job.manager import JobStatus


class JobType(str, Enum):
    """How the job entered the cluster (parity: JobType)."""

    SUBMISSION = "SUBMISSION"  # via the job SDK/CLI/REST
    DRIVER = "DRIVER"  # a bare driver that called init() itself


@dataclasses.dataclass
class DriverInfo:
    """The driver process behind a job (parity: DriverInfo)."""

    id: str
    node_ip_address: str = "127.0.0.1"
    pid: Optional[int] = None


@dataclasses.dataclass
class JobInfo:
    """One job's state snapshot (parity: JobInfo)."""

    status: JobStatus
    entrypoint: str
    submission_id: Optional[str] = None
    message: Optional[str] = None
    metadata: Optional[Dict[str, str]] = None
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    runtime_env: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "JobInfo":
        return cls(
            status=JobStatus(d["status"]),
            entrypoint=d.get("entrypoint", ""),
            submission_id=d.get("submission_id"),
            message=d.get("message"),
            metadata=d.get("metadata"),
            start_time=d.get("start_time"),
            end_time=d.get("end_time"),
            runtime_env=d.get("runtime_env"),
        )


@dataclasses.dataclass
class JobDetails(JobInfo):
    """JobInfo plus identity fields (parity: JobDetails)."""

    type: JobType = JobType.SUBMISSION
    job_id: Optional[str] = None
    driver_info: Optional[DriverInfo] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "JobDetails":
        base = JobInfo.from_dict(d)
        drv = d.get("driver_info")
        return cls(
            **dataclasses.asdict(base),
            type=JobType(d.get("type", "SUBMISSION")),
            job_id=d.get("job_id") or d.get("submission_id"),
            driver_info=DriverInfo(**drv) if isinstance(drv, dict) else drv,
        )
