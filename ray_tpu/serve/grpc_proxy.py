"""gRPC ingress for Serve.

Parity: the reference's per-node ``gRPCProxy`` (``serve/_private/proxy.py:534``,
service schema ``src/ray/protobuf/serve.proto:317`` — ``ListApplications``/
``Healthz`` plus user-defined method handlers routed by the ``application``
request metadata). Here the service is a generic-bytes contract (no protoc
codegen, so user payload schemas stay open):

  /ray_tpu.serve.Serve/Predict           unary-unary, bytes -> bytes
  /ray_tpu.serve.Serve/ListApplications  '' -> JSON list of app names
  /ray_tpu.serve.Serve/Healthz           '' -> b"success"

Routing: request metadata ``application`` picks the app (default:
``default``); ``payload-codec`` metadata selects the codec —
``json`` (default) or ``pickle`` for arbitrary Python/numpy values on both
legs (``content-type`` is reserved by gRPC itself and cannot be user-set).
"""

from __future__ import annotations

import json
import pickle
from concurrent import futures
from typing import Dict, Optional

from ray_tpu.serve.router import DeploymentHandle

_SERVICE = "ray_tpu.serve.Serve"


class GRPCProxy:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        request_timeout_s: float = 30.0,
        *,
        allow_pickle: bool = False,
    ):
        import grpc

        self._grpc = grpc
        self.host = host
        self.request_timeout_s = request_timeout_s
        # pickle deserializes CLIENT-CONTROLLED bytes => arbitrary code
        # execution; only enable on a trusted network (opt-in, like the
        # runtime's own worker channel which assumes a trusted cluster)
        self.allow_pickle = allow_pickle
        self.apps: Dict[str, DeploymentHandle] = {}
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                "Predict": grpc.unary_unary_rpc_method_handler(self._predict),
                "ListApplications": grpc.unary_unary_rpc_method_handler(self._list_apps),
                "Healthz": grpc.unary_unary_rpc_method_handler(self._healthz),
            },
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    # -- handlers (bytes in / bytes out) ------------------------------------
    def _predict(self, request: bytes, context) -> bytes:
        md = {k: v for k, v in (context.invocation_metadata() or ())}
        codec = md.get("payload-codec", "json")
        if codec == "pickle" and not self.allow_pickle:
            # security gate FIRST: never unpickle client bytes un-opted-in,
            # regardless of whether the target app exists
            context.abort(
                self._grpc.StatusCode.INVALID_ARGUMENT,
                "pickle codec disabled; start the proxy with allow_pickle=True "
                "(serve.start(grpc_allow_pickle=True)) on trusted networks only",
            )
        app = md.get("application", "default")
        handle = self.apps.get(app)
        if handle is None:
            context.abort(
                self._grpc.StatusCode.NOT_FOUND,
                f"no application {app!r} (have: {sorted(self.apps)})",
            )
        from ray_tpu.runtime.context import pop_tenant, push_tenant

        tenant_token = push_tenant(md.get("x-tenant-id") or md.get("x-tenant"))
        try:
            if codec == "pickle":
                payload = pickle.loads(request) if request else None
            else:
                payload = json.loads(request) if request else None
            result = handle.remote(payload).result(timeout=self.request_timeout_s)
            if codec == "pickle" and not hasattr(result, "__next__"):
                return pickle.dumps(result)
        except Exception as exc:  # noqa: BLE001
            # HTTP-coherent status mapping (RESOURCE_EXHAUSTED is the 429
            # equivalent; retry_after_s rides the detail string since
            # unary abort has no trailing-metadata helper here)
            from ray_tpu.runtime.admission import grpc_code_for, unwrap

            code_name, retry_after = grpc_code_for(exc)
            cause = unwrap(exc)
            detail = f"{type(cause).__name__}: {cause}"
            if retry_after is not None:
                detail += f" (retry_after_s={retry_after:g})"
            context.abort(getattr(self._grpc.StatusCode, code_name), detail)
        finally:
            pop_tenant(tenant_token)
        if hasattr(result, "__next__"):
            # streaming deployments (stream=True generators) have no
            # unary-gRPC representation; the HTTP proxy serves them as SSE —
            # tell the client instead of dying in json.dumps. OUTSIDE the
            # try: context.abort raises, and the catch-all would rewrite the
            # status to INTERNAL.
            context.abort(
                self._grpc.StatusCode.UNIMPLEMENTED,
                "deployment returned a stream; streaming is not supported "
                "over gRPC Predict — use the HTTP proxy (SSE)",
            )
        try:
            from ray_tpu.serve.proxy import _jsonify

            return json.dumps(result, default=_jsonify).encode()
        except Exception as exc:  # noqa: BLE001
            context.abort(self._grpc.StatusCode.INTERNAL, f"{type(exc).__name__}: {exc}")

    def _list_apps(self, request: bytes, context) -> bytes:
        return json.dumps(sorted(self.apps)).encode()

    def _healthz(self, request: bytes, context) -> bytes:
        return b"success"

    # -- proxy surface (mirrors HTTPProxy) ----------------------------------
    def add_app(self, name: str, handle: DeploymentHandle) -> None:
        self.apps[name] = handle

    def remove_app(self, name: str) -> None:
        self.apps.pop(name, None)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._server.stop(grace=0.5)
