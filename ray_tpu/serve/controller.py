"""ServeController: the reconciling control loop.

Parity: ``python/ray/serve/_private/controller.py:86`` (singleton controller
actor reconciling target vs running replicas per deployment,
``deployment_state.py:1226``) and ``autoscaling_state.py`` (queue-depth
autoscaling between min/max replicas).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.serve.deployment import AutoscalingConfig, Deployment
from ray_tpu.serve.replica import ReplicaActor


class _DeploymentState:
    def __init__(self, deployment: Deployment, init_args: tuple, init_kwargs: dict):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.replicas: List[Any] = []
        self.version = 0
        # disaggregated prefill/decode (serve/disagg.py): per-role replica
        # targets + the role list index-aligned with `replicas` (the router
        # reads it through get_deployment_meta per membership version)
        self.roles: Optional[Dict[str, int]] = (
            dict(deployment.roles) if deployment.roles else None
        )
        self.replica_roles: List[str] = []
        self.role_targets: Dict[str, int] = dict(self.roles or {})
        # decode-pool KV pressure (id(replica) -> free fraction), refreshed
        # by the health-check-cadence probe — the decode pool's autoscaling
        # signal (free pages, not queue depth)
        self.kv_free_frac: Dict[int, float] = {}
        if deployment.autoscaling_config is not None:
            if self.roles is not None:
                self.target_replicas = sum(self.role_targets.values())
            else:
                self.target_replicas = deployment.autoscaling_config.min_replicas
        else:
            self.target_replicas = int(deployment.num_replicas)
        self.last_inflight: Dict[int, int] = {}
        self.last_scale_time = 0.0
        self.health: Dict[int, dict] = {}    # id(replica) -> {fails, born}


@ray_tpu.remote
class ServeControllerActor:
    """Runs in-process; reconcile loop on a background thread."""

    def __init__(self):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._apps: Dict[str, str] = {}  # route_prefix -> ingress deployment
        self._lock = threading.RLock()
        self._changed = threading.Condition(self._lock)  # long-poll wakeups
        self._running = True
        # register on the cluster so chaos hooks (kill_decode_replica) can
        # find live controllers (mirrors cluster.train_controllers)
        try:
            from ray_tpu.runtime.worker import global_worker

            global_worker().cluster.serve_controllers[id(self)] = self
        except Exception:  # noqa: BLE001 — controller driven without rt.init
            pass
        self._reconcile_thread = threading.Thread(target=self._loop, daemon=True, name="serve-reconcile")
        self._reconcile_thread.start()

    # ----------------------------------------------------------- deploys
    def deploy(self, deployment: Deployment, init_args: tuple, init_kwargs: dict) -> None:
        # deploy-time role validation: zero-replica pools or a dense KV
        # cache fail HERE with a typed ValueError, not at the first
        # migration (serve/disagg.py)
        if deployment.roles is not None:
            from ray_tpu.serve.disagg import validate_roles

            validate_roles(deployment.roles, init_kwargs)
        with self._lock:
            old = self._deployments.get(deployment.name)
            state = _DeploymentState(deployment, init_args, init_kwargs)
            if old is not None:
                state.version = old.version
                self._scale_down_locked(old, 0)
            self._deployments[deployment.name] = state
            self._reconcile_locked(state)
            self._changed.notify_all()

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            state = self._deployments.pop(name, None)
            if state is not None:
                self._scale_down_locked(state, 0)
            self._changed.notify_all()

    def set_ingress(self, route_prefix: str, deployment_name: str) -> None:
        with self._lock:
            self._apps[route_prefix] = deployment_name

    def get_ingress_map(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._apps)

    # ----------------------------------------------------------- queries
    def get_replicas(self, name: str) -> Tuple[int, List[Any]]:
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return (-1, [])
            return (state.version, list(state.replicas))

    def poll_replicas(self, name: str, known_version: int, timeout_s: float = 10.0) -> Tuple[int, List[Any]]:
        """Long-poll (parity: LongPollHost, serve/_private/long_poll.py):
        blocks until the replica set's version moves past known_version or
        the timeout lapses, then returns the current snapshot. Routers keep
        one of these outstanding instead of re-pulling on a timer."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while self._running:
                state = self._deployments.get(name)
                current = state.version if state is not None else -1
                if current != known_version:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._changed.wait(remaining):
                    break
            state = self._deployments.get(name)
            if state is None:
                return (-1, [])
            return (state.version, list(state.replicas))

    def list_deployments(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "num_replicas": len(s.replicas),
                    "target_replicas": s.target_replicas,
                    "version": s.deployment.version,
                }
                for name, s in self._deployments.items()
            }

    def get_deployment_meta(self, name: str) -> Dict[str, Any]:
        """Admission/retry knobs the router enforces per deployment
        (fetched on membership changes, not per request)."""
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return {}
            d = state.deployment
            return {
                "max_ongoing_requests": d.max_ongoing_requests,
                "max_queued_requests": d.max_queued_requests,
                "idempotent": d.idempotent,
                # disagg: declared role targets + the per-replica role list,
                # index-aligned with this version's get_replicas snapshot
                "roles": dict(state.roles) if state.roles else None,
                "replica_roles": list(state.replica_roles),
            }

    def record_request_metrics(self, name: str, inflight: Dict[int, int]) -> None:
        with self._lock:
            state = self._deployments.get(name)
            if state is not None:
                state.last_inflight = dict(inflight)

    # ------------------------------------------------------- reconciling
    def _reconcile_locked(self, state: _DeploymentState) -> None:
        before = state.version
        self._reconcile_inner_locked(state)
        if state.version != before:
            # wake long-pollers only on real membership change — an
            # unconditional notify would turn the 0.2s reconcile tick into
            # a busy-poll for every watcher
            self._changed.notify_all()

    def _reconcile_inner_locked(self, state: _DeploymentState) -> None:
        if state.roles is not None:
            self._reconcile_roles_locked(state)
            return
        d = state.deployment
        while len(state.replicas) < state.target_replicas:
            is_function = not isinstance(d.func_or_class, type)
            # the replica-level backstop (handle_request shedding past
            # max_ongoing_requests, +2 concurrency headroom so it is
            # reachable) arms only for deployments that OPTED INTO bounding
            # (max_queued_requests >= 0) — the unbounded default keeps the
            # historical queue-at-the-actor behavior, never a surprise 429
            bounded = d.max_queued_requests >= 0
            replica = ReplicaActor.options(
                execution="inproc",
                max_concurrency=max(2, d.max_ongoing_requests + (2 if bounded else 0)),
                **{k: v for k, v in d.ray_actor_options.items() if k in ("num_cpus", "num_tpus", "resources")},
            ).remote(
                d.func_or_class, state.init_args, state.init_kwargs, d.user_config, is_function,
                deployment=d.name,
                replica_tag=f"{d.name}#{state.version}",
                max_ongoing_requests=d.max_ongoing_requests if bounded else 0,
            )
            state.replicas.append(replica)
            state.version += 1
        if len(state.replicas) > state.target_replicas:
            self._scale_down_locked(state, state.target_replicas)

    def _reconcile_roles_locked(self, state: _DeploymentState) -> None:
        """Reconcile a disaggregated deployment's TWO pools independently:
        each role's replica count converges on its target, and every new
        replica gets ``init_kwargs["role"]`` so the LLM engine knows which
        half of the migration it serves.  Role order is sorted — replica
        creation order (and thus versions and tags) is deterministic."""
        d = state.deployment
        bounded = d.max_queued_requests >= 0
        is_function = not isinstance(d.func_or_class, type)
        for role in sorted(state.role_targets):
            target = max(0, int(state.role_targets[role]))
            count = state.replica_roles.count(role)
            while count < target:
                kwargs = dict(state.init_kwargs)
                kwargs["role"] = role
                replica = ReplicaActor.options(
                    execution="inproc",
                    max_concurrency=max(2, d.max_ongoing_requests + (2 if bounded else 0)),
                    **{k: v for k, v in d.ray_actor_options.items() if k in ("num_cpus", "num_tpus", "resources")},
                ).remote(
                    d.func_or_class, state.init_args, kwargs, d.user_config, is_function,
                    deployment=d.name,
                    replica_tag=f"{d.name}:{role}#{state.version}",
                    max_ongoing_requests=d.max_ongoing_requests if bounded else 0,
                )
                state.replicas.append(replica)
                state.replica_roles.append(role)
                state.version += 1
                count += 1
            while count > target:
                idx = max(
                    i for i, rr in enumerate(state.replica_roles) if rr == role
                )
                replica = state.replicas.pop(idx)
                state.replica_roles.pop(idx)
                state.health.pop(id(replica), None)
                try:
                    ray_tpu.kill(replica)
                except Exception:
                    pass
                state.version += 1
                count -= 1
        state.target_replicas = sum(state.role_targets.values())

    def _scale_down_locked(self, state: _DeploymentState, target: int) -> None:
        while len(state.replicas) > target:
            replica = state.replicas.pop()
            if state.replica_roles:
                state.replica_roles.pop()
            try:
                ray_tpu.kill(replica)
            except Exception:
                pass
            state.version += 1
        if state.roles is not None and target == 0:
            state.role_targets = {r: 0 for r in state.role_targets}

    HEALTH_CHECK_TIMEOUT_S = 5.0
    HEALTH_CHECK_FAILS = 3       # consecutive failures before replacement
    HEALTH_GRACE_S = 15.0        # startup grace before failures count

    def _loop(self) -> None:
        ticks = 0
        # rt-lint: disable=lock-discipline -- one-way stop flag: a stale
        # read costs at most one extra 0.2s control-loop tick
        while self._running:
            time.sleep(0.2)
            ticks += 1
            if ticks % 5 == 0:  # ~1s health-check cadence, outside the lock
                self._health_check()
            if ticks % 5 == 0:
                self._probe_kv_pressure()
            with self._lock:
                for state in list(self._deployments.values()):
                    cfg = state.deployment.autoscaling_config
                    if cfg is not None:
                        if state.roles is not None:
                            self._autoscale_roles_locked(state, cfg)
                        else:
                            self._autoscale_locked(state, cfg)
                    self._reconcile_locked(state)
                    if state.roles is not None and ticks % 5 == 0:
                        self._publish_role_gauges_locked(state)

    def _health_check(self) -> None:
        """Replace replicas that fail HEALTH_CHECK_FAILS consecutive probes
        (parity: DeploymentState replica health checks). Probes run OUTSIDE
        the controller lock — a hung replica must not stall deploys or
        long-pollers — and a startup grace period keeps slow __init__s
        (method calls queue behind them) from being killed mid-load."""
        with self._lock:
            snapshot = {name: list(st.replicas) for name, st in self._deployments.items()}
        refs = {}
        for name, reps in snapshot.items():
            for r in reps:
                try:
                    refs[(name, id(r))] = r.check_health.remote()
                except Exception:
                    refs[(name, id(r))] = None
        from ray_tpu.exceptions import GetTimeoutError

        deadline = time.monotonic() + self.HEALTH_CHECK_TIMEOUT_S
        # "ok" / "slow" (probe timed out: maybe busy or initializing) /
        # "dead" (actor gone: no threshold needed, it can never recover)
        verdicts: Dict[tuple, str] = {}
        for key, ref in refs.items():
            if ref is None:
                verdicts[key] = "dead"
                continue
            try:
                ray_tpu.get(ref, timeout=max(0.1, deadline - time.monotonic()))
                verdicts[key] = "ok"
            except GetTimeoutError:
                verdicts[key] = "slow"
            except Exception:
                verdicts[key] = "dead"
        now = time.monotonic()
        with self._lock:
            for name, reps in snapshot.items():
                state = self._deployments.get(name)
                if state is None:
                    continue
                changed = False
                for r in reps:
                    verdict = verdicts.get((name, id(r)), "ok")
                    rec = state.health.setdefault(
                        id(r), {"fails": 0, "born": now, "ready": False}
                    )
                    if verdict == "ok":
                        rec["fails"] = 0
                        rec["ready"] = True
                        continue
                    rec["fails"] += 1
                    # startup grace ends once the replica has EVER passed a
                    # probe; a dead actor skips the threshold entirely
                    in_grace = not rec["ready"] and now - rec["born"] < self.HEALTH_GRACE_S
                    should_remove = verdict == "dead" or (
                        rec["fails"] >= self.HEALTH_CHECK_FAILS and not in_grace
                    )
                    if should_remove and r in state.replicas:
                        idx = state.replicas.index(r)
                        state.replicas.pop(idx)
                        if idx < len(state.replica_roles):
                            state.replica_roles.pop(idx)
                        state.health.pop(id(r), None)
                        state.kv_free_frac.pop(id(r), None)
                        state.version += 1
                        changed = True
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                        # flight-record the death with the last requests:
                        # which traffic preceded the failed probes is the
                        # first postmortem question
                        try:
                            from ray_tpu.observability import reqtrace

                            reqtrace.flight_record(
                                "replica_died",
                                f"deployment {name!r} replica removed "
                                f"(verdict: {verdict})",
                                severity="WARNING",
                                state={
                                    "deployment": name,
                                    "verdict": verdict,
                                    "fails": rec["fails"],
                                    "replicas_left": len(state.replicas),
                                },
                            )
                        except Exception:  # noqa: BLE001
                            pass
                if changed:
                    self._changed.notify_all()  # routers drop dead replicas now

    def _autoscale_locked(self, state: _DeploymentState, cfg: AutoscalingConfig) -> None:
        """Queue-depth autoscaling (parity: autoscaling_policy.py
        _calculate_desired_num_replicas): desired = ceil(total_ongoing /
        target_ongoing_requests), clamped to [min, max], rate-limited."""
        now = time.monotonic()
        total_ongoing = sum(state.last_inflight.values())
        n = max(1, len(state.replicas))
        desired = math.ceil(total_ongoing / max(cfg.target_ongoing_requests, 1e-9))
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        if desired > state.target_replicas and now - state.last_scale_time >= cfg.upscale_delay_s:
            state.target_replicas = desired
            state.last_scale_time = now
        elif desired < state.target_replicas and now - state.last_scale_time >= cfg.downscale_delay_s:
            state.target_replicas = desired
            state.last_scale_time = now

    # decode pool scales up below this free-page fraction and back down
    # above the high-water (hysteresis gap absorbs admission churn)
    KV_LOW_WATER = 0.2
    KV_HIGH_WATER = 0.8

    def _probe_kv_pressure(self) -> None:
        """Refresh each decode replica's free-KV-page fraction (its pool's
        autoscaling signal).  Probes run OUTSIDE the lock like health
        checks — a busy engine must not stall the control loop."""
        with self._lock:
            targets = []
            for name, state in self._deployments.items():
                if state.roles is None:
                    continue
                for i, r in enumerate(state.replicas):
                    if i < len(state.replica_roles) and state.replica_roles[i] == "decode":
                        targets.append((name, r))
        if not targets:
            return
        results: Dict[tuple, float] = {}
        for name, r in targets:
            try:
                st = ray_tpu.get(
                    r.handle_request.remote("stats", (), {}, None, None),
                    timeout=5.0,
                )
                pool = int(st.get("kv_block_pool_size", 0))
                if pool > 0:
                    results[(name, id(r))] = 1.0 - int(st.get("kv_blocks_in_use", 0)) / pool
            except Exception:  # noqa: BLE001 — probe failure = keep last
                continue
        with self._lock:
            for (name, rid), frac in results.items():
                state = self._deployments.get(name)
                if state is not None:
                    state.kv_free_frac[rid] = frac

    def _autoscale_roles_locked(self, state: _DeploymentState, cfg: AutoscalingConfig) -> None:
        """Per-role autoscaling for a disaggregated deployment: the
        prefill pool scales on queue depth (ongoing requests — prefill is
        compute-bound), the decode pool on free KV pages (decode is
        HBM-bound: a full pool sheds migrations long before its queue
        grows).  Each pool is clamped to [declared count, max_replicas]
        and rate-limited like homogeneous autoscaling."""
        now = time.monotonic()
        declared = state.roles or {}
        ongoing: Dict[str, int] = {}
        for i, r in enumerate(state.replicas):
            role = state.replica_roles[i] if i < len(state.replica_roles) else ""
            ongoing[role] = ongoing.get(role, 0) + state.last_inflight.get(id(r), 0)
        desired: Dict[str, int] = {}
        # prefill: queue-depth signal
        p_min = max(1, int(declared.get("prefill", 1)))
        desired["prefill"] = max(p_min, min(
            max(p_min, cfg.max_replicas),
            math.ceil(ongoing.get("prefill", 0) / max(cfg.target_ongoing_requests, 1e-9)),
        ))
        # decode: free-KV-page signal with hysteresis
        d_min = max(1, int(declared.get("decode", 1)))
        d_max = max(d_min, cfg.max_replicas)
        d_count = state.replica_roles.count("decode")
        fracs = [
            state.kv_free_frac[id(r)]
            for i, r in enumerate(state.replicas)
            if i < len(state.replica_roles)
            and state.replica_roles[i] == "decode"
            and id(r) in state.kv_free_frac
        ]
        d_desired = d_count
        if fracs:
            avg_free = sum(fracs) / len(fracs)
            if avg_free < self.KV_LOW_WATER:
                d_desired = d_count + 1
            elif avg_free > self.KV_HIGH_WATER:
                d_desired = d_count - 1
        desired["decode"] = max(d_min, min(d_max, d_desired))
        for role, want in desired.items():
            cur = state.role_targets.get(role, want)
            if want > cur and now - state.last_scale_time >= cfg.upscale_delay_s:
                state.role_targets[role] = want
                state.last_scale_time = now
            elif want < cur and now - state.last_scale_time >= cfg.downscale_delay_s:
                state.role_targets[role] = want
                state.last_scale_time = now
        state.target_replicas = sum(state.role_targets.values())

    def _publish_role_gauges_locked(self, state: _DeploymentState) -> None:
        from ray_tpu.observability import metric_defs

        name = state.deployment.name
        for role in sorted(state.role_targets):
            count = state.replica_roles.count(role)
            ongoing = sum(
                state.last_inflight.get(id(r), 0)
                for i, r in enumerate(state.replicas)
                if i < len(state.replica_roles) and state.replica_roles[i] == role
            )
            tags = {"deployment": name, "role": role}
            metric_defs.SERVE_POOL_REPLICAS.set(count, tags)
            metric_defs.SERVE_POOL_ONGOING.set(ongoing, tags)

    def pool_status(self) -> Dict[str, dict]:
        """Per-role pool lines for rt llm / GET /api/overload: replica
        count, target, ongoing requests, and (decode) free-KV fraction."""
        with self._lock:
            out: Dict[str, dict] = {}
            for name, state in self._deployments.items():
                if state.roles is None:
                    continue
                pools: Dict[str, dict] = {}
                for role in sorted(state.role_targets):
                    idxs = [
                        i for i, rr in enumerate(state.replica_roles)
                        if rr == role and i < len(state.replicas)
                    ]
                    row = {
                        "replicas": len(idxs),
                        "target": int(state.role_targets.get(role, 0)),
                        "ongoing": sum(
                            state.last_inflight.get(id(state.replicas[i]), 0)
                            for i in idxs
                        ),
                    }
                    if role == "decode":
                        fracs = [
                            state.kv_free_frac[id(state.replicas[i])]
                            for i in idxs
                            if id(state.replicas[i]) in state.kv_free_frac
                        ]
                        if fracs:
                            row["kv_free_frac"] = round(sum(fracs) / len(fracs), 3)
                    pools[role] = row
                out[name] = pools
            return out

    def chaos_kill_replica(self, deployment: str, role: str = "decode",
                           index: int = 0) -> bool:
        """Chaos hook (`kill_decode_replica` schedule kind): kill the
        ``index``-th replica of ``role`` deterministically (list order, no
        randomness — fault logs must be byte-identical across same-seed
        replays).  The reconcile loop replaces it on the next tick."""
        with self._lock:
            state = self._deployments.get(deployment)
            if state is None:
                # default target: the first roles deployment, sorted by
                # name — deterministic, never random
                for name in sorted(self._deployments):
                    if self._deployments[name].roles is not None:
                        state = self._deployments[name]
                        break
            if state is None or state.roles is None:
                return False
            idxs = [
                i for i, rr in enumerate(state.replica_roles)
                if rr == role and i < len(state.replicas)
            ]
            if index >= len(idxs):
                return False
            idx = idxs[index]
            replica = state.replicas.pop(idx)
            state.replica_roles.pop(idx)
            state.health.pop(id(replica), None)
            state.kv_free_frac.pop(id(replica), None)
            state.version += 1
            self._changed.notify_all()
        try:
            ray_tpu.kill(replica)
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
        return True

    # ------------------------------------------------------------- admin
    def shutdown(self) -> None:
        try:
            from ray_tpu.runtime.worker import global_worker

            global_worker().cluster.serve_controllers.pop(id(self), None)
        except Exception:  # noqa: BLE001
            pass
        with self._lock:
            self._running = False
            for state in self._deployments.values():
                self._scale_down_locked(state, 0)
            self._deployments.clear()
            self._apps.clear()
            self._changed.notify_all()

    def ping(self) -> str:
        return "ok"
