"""Declarative Serve config: schema, validation, and deploy-from-config.

Parity: ``python/ray/serve/schema.py`` (``ServeDeploySchema`` /
``ServeApplicationSchema`` / ``DeploymentSchema``) and the config path of
``serve deploy`` — a YAML/dict description of applications:

.. code-block:: yaml

    applications:
      - name: app1
        route_prefix: /app1
        import_path: my_module:app          # module:attr of a bound Application
        deployments:                        # per-deployment overrides
          - name: Model
            num_replicas: 2
            max_ongoing_requests: 16
            autoscaling_config: {min_replicas: 1, max_replicas: 4}

``import_path`` resolves to either a bound ``Application`` (``.bind()``
result) or a ``Deployment`` (bound with no args). Overrides are applied
with ``Deployment.options`` before deploy.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

from ray_tpu.serve.deployment import Application, Deployment

_DEPLOYMENT_OVERRIDE_KEYS = {
    "num_replicas",
    "autoscaling_config",
    "ray_actor_options",
    "max_ongoing_requests",
    "max_queued_requests",
    "idempotent",
    "user_config",
    "version",
    "roles",
}


class ServeConfigError(ValueError):
    pass


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ServeConfigError(msg)


def validate_config(config: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Validate a deploy config dict; returns the application list."""
    _require(isinstance(config, dict), "serve config must be a mapping")
    apps = config.get("applications")
    _require(isinstance(apps, list) and apps, "config needs a non-empty 'applications' list")
    seen_names: set = set()
    seen_prefixes: set = set()
    for app in apps:
        _require(isinstance(app, dict), "each application must be a mapping")
        _require(bool(app.get("import_path")), "application missing 'import_path'")
        name = app.get("name", "default")
        _require(name not in seen_names, f"duplicate application name {name!r}")
        seen_names.add(name)
        prefix = app.get("route_prefix", "/")
        if prefix is not None:
            _require(
                isinstance(prefix, str) and prefix.startswith("/"),
                f"route_prefix must be a string starting with '/': {prefix!r}",
            )
            _require(prefix not in seen_prefixes, f"duplicate route_prefix {prefix!r}")
            seen_prefixes.add(prefix)
        for dep in app.get("deployments", []) or []:
            _require(isinstance(dep, dict) and "name" in dep, "deployment override needs 'name'")
            unknown = set(dep) - _DEPLOYMENT_OVERRIDE_KEYS - {"name"}
            _require(not unknown, f"unknown deployment override keys: {sorted(unknown)}")
    return apps


def import_application(import_path: str) -> Application:
    """Resolve ``module.sub:attr`` to a bound Application."""
    _require(":" in import_path, f"import_path must be 'module:attr', got {import_path!r}")
    module_name, attr = import_path.split(":", 1)
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    if isinstance(target, Deployment):
        target = target.bind()
    _require(
        isinstance(target, Application),
        f"{import_path!r} resolved to {type(target).__name__}, expected a bound Application",
    )
    return target


def apply_overrides(app: Application, overrides: List[Dict[str, Any]]) -> Application:
    """Overridden COPY of the app graph. The input graph is typically the
    module-cached object behind import_path — mutating it would leak one
    deploy's overrides into the next."""
    by_name = {o["name"]: {k: v for k, v in o.items() if k != "name"} for o in overrides}
    if not by_name:
        return app
    used: set = set()
    memo: Dict[int, Application] = {}

    def clone(node: Application) -> Application:
        cached = memo.get(id(node))
        if cached is not None:
            return cached
        args = tuple(clone(a) if isinstance(a, Application) else a for a in node.init_args)
        kwargs = {k: (clone(v) if isinstance(v, Application) else v) for k, v in node.init_kwargs.items()}
        dep = node.deployment
        opts = by_name.get(dep.name)
        if opts is not None:
            used.add(dep.name)
            dep = dep.options(name=dep.name, **opts)
        out = memo[id(node)] = Application(dep, args, kwargs)
        return out

    cloned = clone(app)
    unknown = set(by_name) - used
    _require(not unknown, f"overrides for unknown deployments: {sorted(unknown)}")
    return cloned


def deploy_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Deploy every application in the config; returns a status dict."""
    from ray_tpu import serve

    apps = validate_config(config)
    deployed = {}
    for spec in apps:
        app = import_application(spec["import_path"])
        app = apply_overrides(app, spec.get("deployments", []) or [])
        name = spec.get("name", "default")
        handle = serve.run(app, name=name, route_prefix=spec.get("route_prefix", "/"))
        deployed[name] = {
            "route_prefix": spec.get("route_prefix", "/"),
            "ingress": handle.deployment_name,
        }
    return deployed


def load_config_file(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    _require(isinstance(cfg, dict), f"{path} did not parse to a mapping")
    return cfg
