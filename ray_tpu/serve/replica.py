"""Replica actor: hosts one copy of a deployment's callable.

Parity: ``python/ray/serve/_private/replica.py`` — wraps the user
function/class, counts ongoing requests (the router's pow-2 signal),
applies ``reconfigure`` (user_config), and reports health.
"""

from __future__ import annotations

import contextvars
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import ray_tpu


@dataclass(frozen=True)
class ReplicaContext:
    """What a replica knows about itself (parity: serve.context
    ReplicaContext / serve.get_replica_context)."""

    deployment: str
    replica_tag: str
    app_name: str = "default"
    servable_object: Any = field(default=None, compare=False)


_replica_context: contextvars.ContextVar = contextvars.ContextVar(
    "rt_serve_replica_context", default=None
)


def get_replica_context() -> ReplicaContext:
    """Inside a replica (constructor or request), the replica's identity.
    Contextvar-scoped: replicas can share a process (inproc execution) and
    requests run on pool threads, so a module global would cross-talk."""
    ctx = _replica_context.get()
    if ctx is None:
        raise RuntimeError(
            "get_replica_context() may only be called from within a Serve "
            "replica (a deployment's constructor or request handler)"
        )
    return ctx


@ray_tpu.remote
class ReplicaActor:
    def __init__(self, func_or_class, init_args, init_kwargs, user_config, is_function: bool,
                 deployment: str = "", replica_tag: str = "",
                 max_ongoing_requests: int = 0):
        self.is_function = is_function
        # replica-level admission backstop (0 = unlimited): the router's
        # queue bound is the primary gate, but a replica must defend itself
        # against stale routers too (parity: Serve replicas re-reject past
        # max_ongoing_requests)
        self._max_ongoing = int(max_ongoing_requests)
        self._context = ReplicaContext(deployment=deployment, replica_tag=replica_tag)
        token = _replica_context.set(self._context)
        try:
            if is_function:
                self.callable = func_or_class
            else:
                self.callable = func_or_class(*init_args, **init_kwargs)
                if user_config is not None and hasattr(self.callable, "reconfigure"):
                    self.callable.reconfigure(user_config)
        finally:
            _replica_context.reset(token)
        if not is_function:
            object.__setattr__(self._context, "servable_object", self.callable)
        self._ongoing = 0
        self._lock = threading.Lock()
        self._total = 0

    def handle_request(self, method: str, args: tuple, kwargs: dict,
                       tenant: str = None, trace=None) -> Any:
        from ray_tpu.runtime.context import (
            pop_request_trace,
            pop_tenant,
            push_request_trace,
            push_tenant,
        )

        with self._lock:
            if self._max_ongoing > 0 and self._ongoing >= self._max_ongoing:
                from ray_tpu.runtime import admission

                raise admission.shed(
                    "replica", "queue_full",
                    message=(
                        f"replica {self._context.replica_tag!r} at its "
                        f"max_ongoing_requests bound ({self._max_ongoing})"
                    ),
                )
            self._ongoing += 1
            self._total += 1
        token = _replica_context.set(self._context)
        # the requesting tenant rides proxy header -> handle -> HERE so
        # anything the deployment submits (e.g. LLMEngine admission) sees it
        tenant_token = push_tenant(tenant)
        # the request trace rode the router's explicit argument across the
        # actor boundary; re-install it so the engine stamps its phases
        if trace is not None:
            trace.mark("replica_in")
        trace_token = push_request_trace(trace)
        try:
            if self.is_function:
                return self.callable(*args, **kwargs)
            target = self.callable if method == "__call__" else getattr(self.callable, method)
            if method == "__call__" and not callable(target):
                raise TypeError(f"deployment class {type(self.callable)} is not callable")
            return target(*args, **kwargs) if method != "__call__" else self.callable(*args, **kwargs)
        finally:
            pop_request_trace(trace_token)
            pop_tenant(tenant_token)
            _replica_context.reset(token)
            with self._lock:
                self._ongoing -= 1

    def reconfigure(self, user_config) -> None:
        if not self.is_function and hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)

    # rt-lint: disable=lock-discipline -- autoscaler metric snapshot: a
    # torn counter read skews one poll, never request accounting
    def get_num_ongoing_requests(self) -> int:
        return self._ongoing

    # rt-lint: disable=lock-discipline -- same: observability snapshot
    def get_metrics(self) -> Dict[str, Any]:
        return {"ongoing": self._ongoing, "total": self._total}

    def check_health(self) -> str:
        if not self.is_function and hasattr(self.callable, "check_health"):
            self.callable.check_health()
        return "ok"
