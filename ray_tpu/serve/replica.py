"""Replica actor: hosts one copy of a deployment's callable.

Parity: ``python/ray/serve/_private/replica.py`` — wraps the user
function/class, counts ongoing requests (the router's pow-2 signal),
applies ``reconfigure`` (user_config), and reports health.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import ray_tpu


@ray_tpu.remote
class ReplicaActor:
    def __init__(self, func_or_class, init_args, init_kwargs, user_config, is_function: bool):
        self.is_function = is_function
        if is_function:
            self.callable = func_or_class
        else:
            self.callable = func_or_class(*init_args, **init_kwargs)
            if user_config is not None and hasattr(self.callable, "reconfigure"):
                self.callable.reconfigure(user_config)
        self._ongoing = 0
        self._lock = threading.Lock()
        self._total = 0

    def handle_request(self, method: str, args: tuple, kwargs: dict) -> Any:
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if self.is_function:
                return self.callable(*args, **kwargs)
            target = self.callable if method == "__call__" else getattr(self.callable, method)
            if method == "__call__" and not callable(target):
                raise TypeError(f"deployment class {type(self.callable)} is not callable")
            return target(*args, **kwargs) if method != "__call__" else self.callable(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def reconfigure(self, user_config) -> None:
        if not self.is_function and hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)

    def get_num_ongoing_requests(self) -> int:
        return self._ongoing

    def get_metrics(self) -> Dict[str, Any]:
        return {"ongoing": self._ongoing, "total": self._total}

    def check_health(self) -> str:
        if not self.is_function and hasattr(self.callable, "check_health"):
            self.callable.check_health()
        return "ok"
