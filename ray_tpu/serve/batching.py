"""@serve.batch: dynamic request batching.

Parity: ``python/ray/serve/batching.py`` — queues individual calls and
invokes the wrapped method once per batch (max_batch_size or
batch_wait_timeout_s, whichever first).  This is the TPU money-path: a
batched replica turns N concurrent single requests into one MXU-shaped
batch for the jitted model.
"""

from __future__ import annotations

import functools
import queue
import threading
import weakref
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


def _register_cleanup(instance, key, bq, bq_holder, bq_lock) -> None:
    """Stop the batch thread and drop the holder entry when the replica
    instance is gc'd. Guarded by identity: id() reuse after gc must not
    evict a NEW instance's queue."""

    def cleanup():
        with bq_lock:
            if bq_holder.get(key) is bq:
                del bq_holder[key]
        bq.stop()

    try:
        weakref.finalize(instance, cleanup)
    except TypeError:
        pass  # non-weakref-able instance: entry lives for the process


_STOP = object()


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.queue: "queue.Queue" = queue.Queue()
        self.thread = threading.Thread(target=self._loop, daemon=True, name="serve-batch")
        self.thread.start()

    def submit(self, instance, item) -> Future:
        fut: Future = Future()
        self.queue.put((instance, item, fut))
        return fut

    def stop(self) -> None:
        """Terminate the loop thread (called when the owning replica is
        gc'd — without it every retired replica leaks a thread)."""
        self.queue.put(_STOP)

    def _loop(self) -> None:
        while True:
            got = self.queue.get()
            if got is _STOP:
                return
            instance, item, fut = got
            batch_items = [item]
            futures = [fut]
            deadline = None
            import time

            deadline = time.monotonic() + self.timeout_s
            while len(batch_items) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self.queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    self.queue.put(_STOP)  # re-deliver after this batch
                    break
                batch_items.append(nxt[1])
                futures.append(nxt[2])
            try:
                if instance is not None:
                    results = self.fn(instance, batch_items)
                else:
                    results = self.fn(batch_items)
                if results is None or len(results) != len(batch_items):
                    raise ValueError(
                        f"@serve.batch function must return one result per input "
                        f"(got {None if results is None else len(results)} for {len(batch_items)})"
                    )
                for f, r in zip(futures, results):
                    f.set_result(r)
            except BaseException as exc:  # noqa: BLE001
                for f in futures:
                    if not f.done():
                        f.set_exception(exc)


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorator (parity: serve.batch).  The wrapped fn receives a LIST of
    requests and must return a list of equal length."""

    def wrap(fn):
        bq_holder: dict = {}
        bq_lock = threading.Lock()

        @functools.wraps(fn)
        def method_wrapper(*args, **kwargs):
            if kwargs:
                raise TypeError(
                    "@serve.batch functions take exactly one positional request "
                    f"argument; got keyword arguments {sorted(kwargs)}"
                )
            # Distinguish bound-method vs free-function by arg count.
            if len(args) == 2:
                instance, item = args
            elif len(args) == 1:
                instance, item = None, args[0]
            else:
                raise TypeError("@serve.batch functions take exactly one request argument")
            key = id(instance)
            with bq_lock:
                # Concurrent first calls race here; without the lock each
                # request gets a private queue and batching never happens.
                bq = bq_holder.get(key)
                if bq is None:
                    bq = bq_holder[key] = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                    if instance is not None:
                        _register_cleanup(instance, key, bq, bq_holder, bq_lock)
            return bq.submit(instance, item).result()

        method_wrapper._is_serve_batch = True
        return method_wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
