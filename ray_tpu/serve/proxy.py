"""HTTP proxy: the ingress.

Parity: ``python/ray/serve/_private/proxy.py`` — per-node HTTP ingress
routing requests by path prefix to the app's ingress deployment handle.
The reference uses uvicorn/starlette (ASGI); here a stdlib threading HTTP
server keeps the image dependency-free — each request thread blocks on the
handle's DeploymentResponse, and replica concurrency does the fan-out.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ray_tpu.serve.router import DeploymentHandle


class _ServeHTTPHandler(BaseHTTPRequestHandler):
    proxy: "HTTPProxy" = None  # set by server factory

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _handle(self, body: Optional[bytes]) -> None:
        from urllib.parse import urlsplit

        path = urlsplit(self.path).path  # strip ?query before matching
        handle = None
        for prefix, h in sorted(self.proxy.routes.items(), key=lambda kv: -len(kv[0])):
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                handle = h
                break
        if handle is None:
            self.send_response(404)
            self.end_headers()
            self.wfile.write(b'{"error": "no app at this route"}')
            return
        from ray_tpu.observability import reqtrace
        from ray_tpu.runtime.context import (
            pop_request_trace,
            pop_tenant,
            push_request_trace,
            push_tenant,
        )

        # tenant id rides the ingress header into the request context, then
        # handle -> replica -> engine admission (weighted fairness keys)
        tenant = self.headers.get("X-Tenant-Id") or self.headers.get("X-Tenant")
        tenant_token = push_tenant(tenant)
        # the request trace is BORN here (proxy admission) and rides the
        # same context path; None when disabled or not sampled
        trace = reqtrace.start_trace(
            route=prefix,
            deployment=getattr(handle, "deployment_name", ""),
            tenant=tenant,
        )
        trace_token = push_request_trace(trace)
        outcome, detail = "ok", ""
        try:
            payload: Any = None
            if body:
                try:
                    payload = json.loads(body)
                except json.JSONDecodeError:
                    payload = body.decode("utf-8", "replace")
            result = handle.remote(payload).result(timeout=self.proxy.request_timeout_s)
            if _is_stream(result):
                # generator result (in-proc replica) -> server-sent events,
                # one `data:` frame per item, flushed as produced. Once the
                # 200 + headers are out, a mid-stream failure must NOT fall
                # through to an error status (that writes a second status
                # line into the open body) — emit an error event and close.
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                try:
                    for item in result:
                        frame = json.dumps(item, default=_jsonify)
                        self.wfile.write(f"data: {frame}\n\n".encode())
                        self.wfile.flush()
                except OSError:
                    # the socket died mid-stream: the client went away
                    outcome, detail = "disconnect", "client disconnected mid-stream"
                except Exception as exc:  # noqa: BLE001
                    outcome, detail = _trace_outcome(exc)
                    try:
                        err = json.dumps({"error": str(exc)})
                        self.wfile.write(f"data: {err}\n\n".encode())
                        self.wfile.flush()
                    except OSError:
                        outcome, detail = "disconnect", "client disconnected mid-stream"
                finally:
                    # a disconnected client must FREE its decode slot: close
                    # the generator chain NOW (GeneratorExit propagates into
                    # the engine's stream pump and marks the request
                    # abandoned) instead of waiting for GC to find it
                    close = getattr(result, "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:  # noqa: BLE001
                            pass
                return
            data = json.dumps(result, default=_jsonify).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(data)
        except Exception as exc:  # noqa: BLE001
            # coherent error -> status contract (regression-tested):
            # OverloadedError -> 429 + Retry-After, deadline/timeout -> 504,
            # actor/worker death past the retry budget -> 503, else 500.
            from ray_tpu.runtime.admission import http_status_for, unwrap

            outcome, detail = _trace_outcome(exc)
            status, retry_after = http_status_for(exc)
            cause = unwrap(exc)
            self.send_response(status)
            payload = {"error": str(cause), "type": type(cause).__name__}
            if retry_after is not None:
                self.send_header("Retry-After", str(max(1, int(round(retry_after)))))
                payload["retry_after_s"] = retry_after
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(json.dumps(payload).encode())
        finally:
            pop_request_trace(trace_token)
            pop_tenant(tenant_token)
            # an engine-side terminal (crash/shed/disconnect) claimed first
            # wins: finish_trace's outcome only fills an unclaimed trace
            reqtrace.finish_trace(trace, outcome, detail)

    def do_GET(self):
        self._handle(None)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        self._handle(self.rfile.read(length) if length else None)


def _is_stream(result) -> bool:
    """Iterator/generator results stream as SSE; lists/dicts/strs do not."""
    return hasattr(result, "__next__")


def _trace_outcome(exc: BaseException) -> tuple:
    """Map a request-terminal exception to the trace outcome vocabulary
    (finish/shed/deadline/disconnect/crash are the flight recorder's
    buckets); mirrors admission.http_status_for's type unwrapping."""
    from ray_tpu.exceptions import (
        DeadlineExceededError,
        GetTimeoutError,
        OverloadedError,
        RayActorError,
        WorkerCrashedError,
    )
    from ray_tpu.runtime.admission import unwrap

    cause = unwrap(exc)
    if isinstance(cause, OverloadedError):
        return "shed", f"{cause.layer}:{cause.reason}" if hasattr(cause, "layer") else str(cause)
    if isinstance(cause, (DeadlineExceededError, GetTimeoutError)):
        return "deadline", str(cause)
    if isinstance(cause, (RayActorError, WorkerCrashedError)):
        return "crash", f"{type(cause).__name__}: {cause}"
    return "error", f"{type(cause).__name__}: {cause}"


def _jsonify(obj):
    import numpy as np

    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    return str(obj)


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000, request_timeout_s: float = 30.0):
        self.host = host
        self.port = port
        self.routes: Dict[str, DeploymentHandle] = {}
        self.request_timeout_s = request_timeout_s
        handler = type("Handler", (_ServeHTTPHandler,), {"proxy": self})
        self.server = ThreadingHTTPServer((host, port), handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True, name="serve-proxy")
        self.thread.start()

    def add_route(self, prefix: str, handle: DeploymentHandle) -> None:
        self.routes[prefix] = handle

    def remove_route(self, prefix: str) -> None:
        self.routes.pop(prefix, None)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self.server.shutdown()
        self.server.server_close()
