"""Disaggregated prefill/decode serving: KV-block migration over the
device plane.

Prefill is compute-bound, decode is HBM-bandwidth-bound; co-locating them
on one replica makes every long-prompt burst steal decode compute —
chunked prefill only caps the stall, it doesn't remove it.  Disaggregation
(DistServe, OSDI '24; Splitwise, ISCA '24) splits a deployment declaring
``roles={"prefill": n, "decode": m}`` into two replica pools:

1. the router admits a request into a **prefill** replica (picked by
   queue depth), which runs chunked prefill into its local paged KV and
   parks the resulting block set as a *staged migration*;
2. the block set migrates replica-to-replica over the **device plane**:
   the producer stages each page under a deterministic ``(request, block)``
   uuid (:func:`migration_uuid`) via the transfer server, and the decode
   replica — picked by free KV pages — pulls device-to-device.  The
   control stream carries only the block-table header (:class:`ticket
   <make_ticket>`), zero KV payload bytes;
3. the decode replica's continuous batcher adopts the blocks into its own
   pool (COW / prefix-cache semantics intact) and resumes decode from the
   migrated block table.

Handoff state machine (one migration)::

    prefill-done ──> staging ──> pulled ──> decoding ──> finished
         │              │           │
         │              └───────────┴──[decode replica died / refused]
         │                          ▼
         └────────────────── re-prefill fallback (fresh attempt id)

Ladder per block: in-process staged copy (same-process replicas
short-circuit — identical bytes, zero copies) → device pull (transfer
server) → host-staged pull (data-plane ``kv_pull`` op).  Fallback ladder
per migration: retry with a re-prefill on a fresh replica pair, at most
``Config.kv_migration_attempts`` attempts, then the typed
:class:`KVMigrationError` surfaces to the caller.

Determinism contract: migration ids derive from a per-dispatcher monotonic
counter + attempt index (never random), and block uuids derive from the
migration id — same-seed chaos runs replay byte-identical fault logs.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.config import get_config
from ray_tpu.observability import metric_defs

#: the two pool roles a disaggregated deployment declares
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"

_OUTCOME_TAGS = {
    "device": {"outcome": "device"},
    "host": {"outcome": "host"},
    "reprefill": {"outcome": "reprefill"},
    "failed": {"outcome": "failed"},
}


class KVMigrationError(RuntimeError):
    """Typed failure of one KV-block migration attempt (decode replica
    died, refused the pull, or lost the staged blocks).  The dispatcher
    catches it to walk the fallback ladder; callers see it only when the
    ladder is exhausted."""

    def __init__(self, mig_id: str, stage: str, message: str):
        super().__init__(f"kv migration {mig_id!r} failed at {stage}: {message}")
        self.mig_id = mig_id
        self.stage = stage


def migration_uuid(mig_id: str, block_idx: int) -> int:
    """Deterministic transfer-server uuid for one staged block: derived
    from the ``(request, block)`` identity, NEVER random — chaos runs must
    replay identical wire traffic.  Mirrors the compiled-plan channel's
    ``_device_frame_uuid`` derivation (crc32 keyspace partitioned by a
    tagged prefix; low 32 bits carry the block index)."""
    hi = zlib.crc32(f"kvmig:{mig_id}".encode()) & 0x7FFFFFFF
    return ((hi << 32) | (block_idx & 0xFFFFFFFF)) or 1


def validate_roles(roles: Optional[Dict[str, int]],
                   init_kwargs: Optional[dict] = None) -> None:
    """Deploy-time validation of a disaggregated deployment (fails fast
    with a typed ValueError instead of wedging at the first migration):

    - only the ``prefill`` / ``decode`` roles exist;
    - both pools need at least one replica (zero decode replicas would
      accept prefills that can never decode);
    - ``llm_cache_kind="dense"`` has no block table to migrate — roles
      require the paged KV cache.
    """
    if roles is None:
        return
    unknown = sorted(set(roles) - {ROLE_PREFILL, ROLE_DECODE})
    if unknown:
        raise ValueError(
            f"unknown deployment role(s) {unknown}: a disaggregated "
            f"deployment declares only {ROLE_PREFILL!r} and {ROLE_DECODE!r}"
        )
    for role in (ROLE_PREFILL, ROLE_DECODE):
        if int(roles.get(role, 0)) < 1:
            raise ValueError(
                f"roles={roles} needs at least one {role!r} replica: a "
                "disaggregated deployment admits into the prefill pool and "
                "decodes on the decode pool — an empty pool wedges every "
                "request at its first migration"
            )
    kind = (init_kwargs or {}).get("cache_kind")
    if kind is None:
        kind = get_config().llm_cache_kind
    if kind == "dense":
        raise ValueError(
            "roles= requires the paged KV cache (llm_cache_kind='paged'): "
            "a dense cache has no block table to migrate between replicas"
        )


def make_ticket(
    mig_id: str,
    *,
    prompt: List[int],
    tok0: int,
    n_blocks: int,
    block_size: int,
    block_shape: Tuple[int, ...],
    block_dtype: str,
    transfer_addr: Optional[str],
    data_addr: Optional[str],
    source: str,
) -> dict:
    """The migration's control-stream header: block-table metadata only —
    the KV payload rides the device plane (or the host-staged fallback),
    never this dict.  ``source`` names the prefill replica for in-process
    staged-copy resolution and audit attribution."""
    return {
        "mig_id": mig_id,
        "prompt": list(prompt),
        "tok0": int(tok0),
        "n_blocks": int(n_blocks),
        "block_size": int(block_size),
        "block_shape": tuple(block_shape),
        "block_dtype": str(block_dtype),
        "transfer_addr": transfer_addr,
        "data_addr": data_addr,
        "source": source,
    }


_planes: Optional[Tuple[Any, Any]] = None


def _runtime_planes() -> Tuple[Any, Any]:
    """``(data_plane, device_plane)``, imported once.  pull_block runs per
    staged block; re-resolving a package ``from``-import there costs ~100us
    a call and dominated the whole migration wall."""
    global _planes
    if _planes is None:
        from ray_tpu.runtime import data_plane, device_plane

        _planes = (data_plane, device_plane)
    return _planes


def pull_block(ticket: dict, block_idx: int,
               timeout_s: Optional[float] = None) -> Tuple[Any, str]:
    """Fetch one staged block for ``ticket``, walking the per-block rungs:
    in-process staged copy → device pull → host-staged ``kv_pull``.
    Returns ``(array, rung)``; raises :class:`KVMigrationError` when every
    rung refuses (the per-migration ladder then re-prefills)."""
    import jax.numpy as jnp
    import numpy as np

    data_plane, device_plane = _runtime_planes()

    mig_id = ticket["mig_id"]
    if timeout_s is None:
        timeout_s = get_config().kv_migration_pull_timeout_s
    # same-process replicas (inproc execution) short-circuit FIRST: a
    # registry hit means the prefill replica staged these very arrays in
    # this process — identical bytes with zero copies, so round-tripping
    # them through a socket (or the transfer server) would only add
    # serialization cost.  Staged blocks are already device arrays;
    # re-wrapping through jnp.asarray costs a dispatch per block for
    # nothing, so only host arrays get converted.
    fetch = data_plane.kv_block_source(mig_id)
    if fetch is not None:
        import jax

        try:
            arr = fetch(block_idx)
        except Exception as exc:  # noqa: BLE001 — released mid-pull
            raise KVMigrationError(
                mig_id, "pulled", f"staged block {block_idx} lost: {exc!r}"
            ) from exc
        if not isinstance(arr, jax.Array):
            arr = jnp.asarray(arr)
        return arr, "host"
    addr = ticket.get("transfer_addr")
    if addr:
        template = np.zeros(
            ticket["block_shape"], np.dtype(ticket["block_dtype"])
        )
        arr = device_plane.device_pull(
            addr, migration_uuid(mig_id, block_idx), template
        )
        if arr is not None:
            return arr, "device"
    data_addr = ticket.get("data_addr")
    if data_addr:
        arr = data_plane.pull_kv_block(
            data_addr, mig_id, block_idx, timeout=timeout_s
        )
        if arr is not None:
            return jnp.asarray(arr), "host"
    raise KVMigrationError(
        mig_id, "staging",
        f"block {block_idx}: no rung could reach the staged page "
        f"(transfer_addr={addr!r}, data_addr={data_addr!r})",
    )


def local_data_addr() -> Optional[str]:
    """Address of this node's data server (the host-staged fallback
    endpoint a ticket advertises), or None when the engine runs without a
    runtime — the in-process registry rung still works then."""
    try:
        from ray_tpu.runtime.worker import global_worker

        return global_worker().cluster.head_service.data_server.address
    except Exception:  # noqa: BLE001 — engine driven without rt.init
        return None


def _record_audit(event: dict) -> None:
    """Append one migration-lifecycle audit onto the cluster (the chaos
    invariant sweep asserts every staged block set reaches exactly one
    terminal).  Best-effort: engines driven without a runtime still work."""
    try:
        from ray_tpu.runtime.worker import global_worker

        cluster = global_worker().cluster
        audits = getattr(cluster, "kv_migration_audits", None)
        if audits is not None:
            audits.append(event)
    except Exception:  # noqa: BLE001 — audits must never fail a request
        pass


class DisaggDispatcher:
    """Role-aware request flow for one disaggregated deployment.

    Owned by the router (one per deployment with ``roles``); uses the
    router's replica list + metadata and calls replicas through the same
    ``handle_request`` surface as ordinary dispatch, so admission bounds,
    tenant context, and the request trace all ride along unchanged.
    """

    def __init__(self, router, deployment: str):
        self._router = router
        self._deployment = deployment
        self._lock = threading.Lock()
        self._seq = 0
        # monotonic dispatch counters per role (rt llm / /api/overload)
        self.dispatched = {ROLE_PREFILL: 0, ROLE_DECODE: 0}
        self.migrations = {k: 0 for k in _OUTCOME_TAGS}

    # ------------------------------------------------------------ identity
    def _next_mig_id(self) -> str:
        """Derived, never random: ``<deployment>/m<seq>`` with the attempt
        suffix appended per ladder rung — byte-identical across same-seed
        chaos replays."""
        with self._lock:
            self._seq += 1
            return f"{self._deployment}/m{self._seq}"

    # ------------------------------------------------------------ dispatch
    def route(self, request: dict, tenant=None, trace=None):
        """Full disaggregated flow for one request: prefill → migrate →
        decode, with the re-prefill fallback ladder."""
        from ray_tpu.runtime import failpoints

        attempts = max(1, int(get_config().kv_migration_attempts))
        base_id = self._next_mig_id()
        last_exc: Optional[BaseException] = None
        for attempt in range(attempts):
            mig_id = base_id if attempt == 0 else f"{base_id}#a{attempt}"
            t0 = time.perf_counter()
            # prefill-pool failures raise as ordinary request errors, not
            # migration failures: no staged state exists yet
            p_index, ticket = self._prefill(request, mig_id, tenant, trace)
            _record_audit({
                "mig_id": mig_id,
                "event": "staged",
                "deployment": self._deployment,
                "blocks": ticket["n_blocks"],
                "attempt": attempt,
            })
            try:
                hit = failpoints.fp("disagg.decode_call")
                if hit == "raise":  # pragma: no cover — fp() raises itself
                    raise KVMigrationError(mig_id, "staging", "failpoint")
                result, rung = self._decode(request, ticket, tenant, trace)
            except BaseException as exc:  # noqa: BLE001 — ladder catches all
                self._release(p_index, mig_id,
                              "reprefill" if attempt + 1 < attempts
                              else "failed", tenant)
                last_exc = exc
                if attempt + 1 < attempts:
                    outcome = "reprefill"
                    self.migrations[outcome] += 1
                    metric_defs.LLM_KV_MIGRATIONS.inc(tags=_OUTCOME_TAGS[outcome])
                    continue
                self.migrations["failed"] += 1
                metric_defs.LLM_KV_MIGRATIONS.inc(tags=_OUTCOME_TAGS["failed"])
                raise KVMigrationError(
                    mig_id, "pulled",
                    f"fallback ladder exhausted after {attempts} attempt(s): "
                    f"{exc!r}",
                ) from exc
            # decode replica owns its copies now: drop the staged set on
            # the prefill side (its pages already retired into the prefill
            # replica's prefix cache at export)
            self._release(p_index, mig_id, "adopted", tenant)
            self.migrations[rung] += 1
            metric_defs.LLM_KV_MIGRATIONS.inc(tags=_OUTCOME_TAGS[rung])
            metric_defs.LLM_KV_MIGRATION_SECONDS.observe(time.perf_counter() - t0)
            if isinstance(result, dict) and "_stream" in result:
                # streaming decode: hand the per-token event generator
                # straight to the proxy, like the homogeneous path does
                return result["_stream"]
            return result
        raise last_exc  # pragma: no cover — loop always returns or raises

    # ------------------------------------------------------------ replicas
    def _call(self, index: int, method: str, args: tuple, tenant, trace,
              timeout: Optional[float] = None):
        return self._router.call_replica(
            self._deployment, index, method, args, tenant, trace,
            timeout=timeout,
        )

    def _prefill(self, request: dict, mig_id: str, tenant,
                 trace) -> Tuple[int, dict]:
        index = self._router.pick_role_replica(
            self._deployment, ROLE_PREFILL, signal="queue"
        )
        self.dispatched[ROLE_PREFILL] += 1
        ticket = self._call(
            index, "disagg_prefill", (dict(request), mig_id), tenant, trace
        )
        if not isinstance(ticket, dict) or "mig_id" not in ticket:
            raise KVMigrationError(
                mig_id, "prefill-done",
                f"prefill replica returned no ticket: {type(ticket)}",
            )
        return index, ticket

    def _decode(self, request: dict, ticket: dict, tenant, trace):
        index = self._router.pick_role_replica(
            self._deployment, ROLE_DECODE, signal="kv_free"
        )
        self.dispatched[ROLE_DECODE] += 1
        out = self._call(
            index, "disagg_decode", (dict(request), ticket), tenant, trace
        )
        if isinstance(out, dict) and out.pop("_kv_migration_error", None):
            raise KVMigrationError(
                ticket["mig_id"], out.get("stage", "pulled"),
                out.get("message", "decode replica refused the migration"),
            )
        rung = "device"
        if isinstance(out, dict):
            rung = out.pop("_migration_rung", "device")
        return out, rung

    def _release(self, p_index: int, mig_id: str, outcome: str,
                 tenant) -> None:
        """Drop the staged block set on the prefill side — exactly once
        per migration, whatever the outcome.  Best-effort: if the prefill
        replica itself died, the transfer server's TTL reaps its offers
        and the process-global source registry entry dies with it."""
        try:
            self._call(p_index, "disagg_release", (mig_id,), tenant, None)
        except Exception:  # noqa: BLE001 — TTL reaps stragglers
            pass
        _record_audit({
            "mig_id": mig_id,
            "event": "released",
            "deployment": self._deployment,
            "outcome": outcome,
        })

    # --------------------------------------------------------------- stats
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dispatched": dict(self.dispatched),
                "migrations": dict(self.migrations),
            }
