"""Deployments: the unit of serving.

Parity: ``python/ray/serve/deployment.py`` + ``api.py`` — ``@serve.deployment``
wraps a class or function; ``.options()`` tweaks replica count/resources;
``.bind(*args)`` builds the composition graph (args may be other bound
deployments, which materialize as ``DeploymentHandle``s at run time).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union


@dataclass
class AutoscalingConfig:
    """Parity: serve autoscaling_policy.py basic config."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


class Deployment:
    def __init__(
        self,
        func_or_class: Union[Callable, type],
        name: str,
        *,
        num_replicas: Union[int, str] = 1,
        autoscaling_config: Optional[Union[dict, AutoscalingConfig]] = None,
        ray_actor_options: Optional[dict] = None,
        max_ongoing_requests: int = 100,
        max_queued_requests: int = -1,
        idempotent: bool = False,
        user_config: Optional[dict] = None,
        version: str = "1",
        roles: Optional[Dict[str, int]] = None,
    ):
        self.func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        # Disaggregated serving (serve/disagg.py): ``roles={"prefill": n,
        # "decode": m}`` materializes two independently-scaled replica
        # pools instead of one homogeneous set; the router migrates each
        # request's KV blocks from its prefill replica to a decode replica
        # over the device plane.  None = classic homogeneous deployment.
        # Validated at deploy time (controller.deploy -> validate_roles).
        self.roles = dict(roles) if roles else None
        if self.roles is not None:
            self.num_replicas = sum(int(v) for v in self.roles.values())
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        if num_replicas == "auto" and autoscaling_config is None:
            # Parity: num_replicas="auto" enables default autoscaling.
            autoscaling_config = AutoscalingConfig()
        self.autoscaling_config = autoscaling_config
        self.ray_actor_options = ray_actor_options or {}
        self.max_ongoing_requests = max_ongoing_requests
        # Admission bound on requests WAITING for this deployment beyond
        # its replicas' concurrency (parity: serve's max_queued_requests
        # rejection path).  -1 = unbounded (the historical behavior); past
        # the bound the router sheds with OverloadedError -> HTTP 429.
        self.max_queued_requests = max_queued_requests
        # Replica-death replay gate: only idempotent deployments may have a
        # request REPLAYED after its replica died mid-flight (the original
        # may have executed its side effects before dying).  Default False:
        # at-most-once — the caller sees the typed actor error and decides.
        self.idempotent = idempotent
        self.user_config = user_config
        self.version = version

    def options(self, **kwargs) -> "Deployment":
        merged = dict(
            num_replicas=self.num_replicas,
            autoscaling_config=self.autoscaling_config,
            ray_actor_options=self.ray_actor_options,
            max_ongoing_requests=self.max_ongoing_requests,
            max_queued_requests=self.max_queued_requests,
            idempotent=self.idempotent,
            user_config=self.user_config,
            version=self.version,
            roles=self.roles,
        )
        name = kwargs.pop("name", self.name)
        merged.update(kwargs)
        return Deployment(self.func_or_class, name, **merged)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self) -> str:
        return f"Deployment(name={self.name!r}, num_replicas={self.num_replicas})"


class Application:
    """A bound deployment DAG node (parity: serve Application from .bind())."""

    def __init__(self, deployment: Deployment, init_args: Tuple, init_kwargs: dict):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs

    def walk(self) -> List["Application"]:
        """All Applications in this graph, dependencies first."""
        seen: List[Application] = []

        def visit(app: Application):
            for a in list(app.init_args) + list(app.init_kwargs.values()):
                if isinstance(a, Application):
                    visit(a)
            if app not in seen:
                seen.append(app)

        visit(self)
        return seen


def deployment(
    _func_or_class: Optional[Union[Callable, type]] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Union[int, str] = 1,
    autoscaling_config: Optional[Union[dict, AutoscalingConfig]] = None,
    ray_actor_options: Optional[dict] = None,
    max_ongoing_requests: int = 100,
    max_queued_requests: int = -1,
    idempotent: bool = False,
    user_config: Optional[dict] = None,
    version: str = "1",
    roles: Optional[Dict[str, int]] = None,
):
    """``@serve.deployment`` (parity: serve/api.py:deployment)."""

    def wrap(fc):
        return Deployment(
            fc,
            name or getattr(fc, "__name__", "deployment"),
            num_replicas=num_replicas,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            idempotent=idempotent,
            user_config=user_config,
            version=version,
            roles=roles,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
