"""LLM serving: continuous batching over a shared KV cache.

The reference's Serve ships no inference engine (its LLM guides delegate to
vLLM on GPU). On TPU the engine IS the framework's job, and the design is
dictated by XLA's static-shape compilation model:

- **Fixed decode slots.** B = ``max_batch_size`` decode slots; a request
  occupies a slot from admission to completion and every decode step is ONE
  jitted program over all B slots (inactive slots compute masked garbage —
  the static-shape price, paid in exchange for zero recompiles at any
  admission pattern).
- **Paged KV cache (default).** K/V live in a shared HBM pool of
  fixed-size pages ``[L, num_blocks, block_size, Hkv, Dh]``; each slot
  names its pages in a static-shape ``int32[B, max_blocks_per_slot]`` block
  table (PagedAttention, Kwon et al. 2023). Admission is block-aware — a
  request is admitted when enough PAGES are free, so HBM capacity is
  proportional to tokens actually reserved, not ``B * max_len``. The
  ``"dense"`` cache kind keeps the classic one-row-per-slot
  ``[L, B, Hkv, S, Dh]`` buffer.
- **Chunked prefill.** Prompts prefill in fixed-size chunks interleaved
  between decode steps (Sarathi-style bounded per-iteration budget,
  ``prefill_chunk_tokens``; 0 = one-shot with power-of-2 bucketing), so a
  long prompt stalls running decodes by at most one chunk's forward.
- **Prefix-aware KV reuse (paged engines, on by default).** Finished
  requests publish the full blocks of prompt+completion into a radix
  prefix cache (``serve/prefix_cache.py``); admission matches the longest
  cached prefix and ``share()``s those pages straight into the new block
  table, so prefill starts at the first UNCACHED token and reserves pool
  budget only for the suffix. Pages are refcounted; a write that would
  land in a shared page goes through copy-on-write; when the pool runs
  short, unreferenced cached leaves are LRU-evicted before admission holds
  or sheds (vLLM PagedAttention / SGLang RadixAttention idiom).
- **Continuous batching.** New requests join between decode steps
  (vLLM-style iteration-level scheduling); finished ones free their slot
  and pages immediately. Per-request ``max_tokens`` and ``temperature``
  ride as device arrays, so mixed sampling configs share one compiled step.

``LLMServer`` is the Serve-facing wrapper: a deployment class whose
replicas each own an engine; requests arrive via handle/HTTP and block on a
per-request Future.
"""

from __future__ import annotations

import functools
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.core.config import get_config
from ray_tpu.exceptions import DeadlineExceededError
from ray_tpu.models.generation import (
    copy_paged_page,
    decode_step,
    filter_top_k_top_p,
    forward_with_cache,
    init_cache,
    init_paged_cache,
    paged_decode_step,
    paged_forward_with_cache,
)
from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.observability import metric_defs
from ray_tpu.observability.sketch import LatencySketch
from ray_tpu.runtime import admission
from ray_tpu.runtime.context import (
    current_deadline_ts,
    current_request_trace,
    current_tenant,
)
from ray_tpu.serve.kv_blocks import BlockAllocator
from ray_tpu.serve.prefix_cache import PrefixCache

_STREAM_END = object()

# prebuilt tag dicts for the per-request admission hot path
_EVICT_DISCONNECT_TAGS = {"reason": "disconnect"}
_PREFIX_RESULT_TAGS = {
    "hit": {"result": "hit"},
    "partial": {"result": "partial"},
    "miss": {"result": "miss"},
}


@dataclass
class GenRequest:
    prompt: List[int]
    max_tokens: int = 32
    temperature: float = 0.0
    eos_id: Optional[int] = None
    future: Future = field(default_factory=Future)
    stream_queue: Optional[Any] = None  # queue.Queue when streaming
    # admission metadata: the requesting tenant (weighted fairness key) and
    # the PR-8 deadline riding the request context — an expired deadline
    # sheds on arrival so doomed work never occupies a decode slot
    tenant: Optional[str] = None
    deadline_ts: Optional[float] = None
    # consumer-gone flag (streaming): the stream pump marks an abandoned
    # iterator and the engine evicts the decode slot instead of generating
    # for nobody
    cancelled: bool = False
    # filled by the engine
    slot: int = -1
    generated: List[int] = field(default_factory=list)
    # chunked prefill progress: prompt tokens already cached (paged engine)
    prefill_pos: int = 0
    # request-scope observability: the lifecycle trace born at the proxy
    # (None when tracing is off, the request skipped sampling, or the
    # engine is driven directly without a serve ingress) plus engine-side
    # perf_counter stamps that feed the per-engine latency sketches
    # whether or not a trace is riding along
    trace: Optional[Any] = None
    t_submit: float = 0.0
    t_first: float = 0.0
    t_last_tok: float = 0.0
    # queue-wait observed exactly once (a held head-of-line request is
    # resumed through _pop_admissible again and must not double-count)
    wfq_popped: bool = False
    # disaggregated serving (serve/disagg.py): an EXPORT request runs
    # chunked prefill, then stages its block set under this migration id
    # and resolves its future with a ticket instead of decoding; an IMPORT
    # request carries the producer's ticket + pulled block arrays and
    # joins the decode batch without prefilling
    export_mig_id: Optional[str] = None
    import_ticket: Optional[dict] = None
    import_arrays: Optional[Dict[int, Any]] = None

    def emit(self, tok: int) -> None:
        if self.stream_queue is not None:
            self.stream_queue.put(tok)


class _TokenStream:
    """Iterator over a streaming request's tokens whose ``close()`` (called
    explicitly, via GC of an abandoned iterator, or by GeneratorExit
    propagation from a disconnected SSE client) marks the request
    ABANDONED — the engine frees its decode slot (or its waiting-queue
    budget, if never admitted) instead of generating for nobody.  A plain
    generator's finally-block cannot do this: closing a generator that
    never started skips its body entirely."""

    __slots__ = ("_gen", "_req", "_engine")

    def __init__(self, gen, req: GenRequest, engine: "LLMEngine"):
        self._gen = gen
        self._req = req
        self._engine = engine

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self) -> None:
        self._gen.close()
        if not self._req.future.done():
            self._engine._abandon_stream(self._req)

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — GC teardown must never raise
            pass


def _bucket(n: int, lo: int = 16, cap: Optional[int] = None) -> int:
    """Smallest power-of-2 bucket >= n (floored at ``lo``), clamped to
    ``cap``. A length past the cap raises — the caller surfaces it as the
    typed never-fits ``ValueError`` at submit instead of letting the bucket
    grow past the cache and failing deep inside prefill."""
    if cap is not None and n > cap:
        raise ValueError(f"length {n} exceeds the cache capacity {cap}")
    b = lo
    while b < n:
        b *= 2
    return b if cap is None else min(b, cap)


class LLMEngine:
    """Continuous-batching decode engine for one model on one device/mesh.

    Thread model: callers enqueue via :meth:`submit` (thread-safe); one
    background loop admits requests and steps the batch. All jitted callables
    are built once in __init__ so the loop never traces.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Any,
        *,
        max_batch_size: int = 8,
        max_seq_len: int = 512,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        quantize: bool = False,
        quantize_min_size: int = 4096,
        mesh: Optional[Any] = None,
        tp: str = "tp",
        decode_chunk: int = 1,
        max_queued_requests: int = 256,
        max_queued_prefill_tokens: int = 0,
        tenant_weights: Optional[Dict[str, float]] = None,
        cache_kind: Optional[str] = None,
        kv_block_size: Optional[int] = None,
        kv_num_blocks: Optional[int] = None,
        prefill_chunk_tokens: Optional[int] = None,
        prefix_cache: Optional[bool] = None,
        prefix_cache_max_blocks: Optional[int] = None,
        role: Optional[str] = None,
    ):
        self.cfg = cfg
        self.B = max_batch_size
        self.S = max_seq_len
        # disaggregated pool role ("prefill"/"decode", "" = co-located).
        # Informational except for validation: either role can run either
        # path, the router just never sends a prefill replica decodes.
        if role not in (None, "", "prefill", "decode"):
            raise ValueError(f"role must be 'prefill' or 'decode', got {role!r}")
        self.role = role or ""
        # KV layout: "paged" (block pool + per-slot block tables) is the
        # default via Config.llm_cache_kind; explicit args override the
        # config knobs. Engines under a mesh auto-fall back to dense — the
        # GSPMD sharding of the paged scatter/gather is not wired yet.
        rc = get_config()
        kind = cache_kind if cache_kind is not None else rc.llm_cache_kind
        if kind == "paged" and mesh is not None:
            if cache_kind is not None:
                raise ValueError("cache_kind='paged' with a mesh is not supported yet")
            kind = "dense"
        if kind not in ("dense", "paged"):
            raise ValueError(f"cache_kind must be 'dense' or 'paged', got {kind!r}")
        if self.role and kind != "paged":
            raise ValueError(
                f"role={self.role!r} requires the paged KV cache: a dense "
                "cache has no block table to migrate between replicas"
            )
        self.cache_kind = kind
        self.kv_block_size = int(
            kv_block_size if kv_block_size is not None else rc.kv_block_size
        )
        if self.kv_block_size < 1:
            raise ValueError(f"kv_block_size must be >= 1, got {self.kv_block_size}")
        # static block-table width: enough logical blocks for a max-length
        # sequence — the table shape never depends on the allocation pattern
        self.max_blocks_per_slot = -(-self.S // self.kv_block_size)
        nb = int(kv_num_blocks if kv_num_blocks is not None else rc.kv_num_blocks)
        if nb <= 0:
            # auto: dense-equivalent capacity (+1 for the garbage page)
            nb = self.B * self.max_blocks_per_slot + 1
        self.kv_num_blocks = nb
        self.prefill_chunk_tokens = int(
            prefill_chunk_tokens if prefill_chunk_tokens is not None
            else rc.prefill_chunk_tokens
        )
        self._allocator = BlockAllocator(nb) if kind == "paged" else None
        # prefix-aware KV reuse is a paged-pool feature (dense engines have
        # no pages to share); on by default via Config.llm_prefix_cache
        use_prefix = prefix_cache if prefix_cache is not None else rc.llm_prefix_cache
        pcb = int(
            prefix_cache_max_blocks if prefix_cache_max_blocks is not None
            else rc.prefix_cache_max_blocks
        )
        self._prefix = (
            PrefixCache(self.kv_block_size, pcb)
            if (kind == "paged" and use_prefix)
            else None
        )
        # prefix-cache outcome counts per admitted request, tokens whose
        # prefill compute was skipped, and copy-on-write page copies
        self._prefix_results = {"hit": 0, "partial": 0, "miss": 0}
        self._prefix_tokens_reused = 0
        self._cow_count = 0
        # bounded waiting queue (overload survival, ISSUE 9): past the
        # request-count bound, or the prefill-token budget (0 = unbounded),
        # submit() sheds with a typed OverloadedError instead of growing
        # the waiting list while decode falls behind
        self._max_queued = max(0, int(max_queued_requests))
        self._max_queued_tokens = max(0, int(max_queued_prefill_tokens))
        self._queued_tokens = 0
        self.num_slots_evicted = 0
        self.num_shed = 0
        self._prefill_count = 0  # prompts fully prefilled
        # tokens generated per host round trip (1 = per-token stepping).
        # >1 amortizes dispatch/readback latency; admission and stream
        # emission happen at chunk granularity, and a request finishing
        # mid-chunk discards the tail tokens (identical outputs either way)
        self.decode_chunk = max(1, int(decode_chunk))
        self.top_k = top_k
        self.top_p = top_p
        self.quantized = quantize
        self.mesh = mesh
        self._kv_spec = None
        if mesh is not None:
            # tensor-parallel serving: params shard per the Megatron layout
            # (ray_tpu.models.transformer.param_specs), the KV cache's head
            # axis over tp when divisible; GSPMD partitions the einsum
            # attention, so decode collectives ride ICI. The Pallas decode
            # kernel is bypassed (it would need a shard_map wrapper).
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ray_tpu.models.transformer import _kv_tp_ok, shard_params

            if quantize:
                raise ValueError("quantize=True with mesh is not supported yet")
            if tp not in mesh.axis_names:
                raise ValueError(f"mesh has no {tp!r} axis: {mesh.axis_names}")
            params = shard_params(params, mesh, cfg, tp=tp, ep=tp)
            kv_ax = tp if _kv_tp_ok(cfg, mesh, tp) else None
            self._kv_spec = NamedSharding(mesh, P(None, None, kv_ax, None, None))
        if quantize:
            # weight-only int8 on the stacked layer LINEAR weights (norm
            # gains and the embedding stay full precision). Scales ride the
            # layer scan as xs, so dequant happens per layer IN the scan
            # body — only one layer is ever wide, never a whole-tree copy.
            from ray_tpu.ops.quantization import quantize_layers

            q_layers, self._layer_scales = quantize_layers(
                params["layers"], min_size=quantize_min_size
            )
            self.params = {**params, "layers": q_layers}
        else:
            self._layer_scales = None
            self.params = params

        # tenant-keyed weighted fair queue: pops interleave proportionally
        # to tenant_weights (default weight 1), so one hot tenant saturating
        # the queue cannot starve the others' admissions
        self._queue = admission.WeightedFairQueue(tenant_weights)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._admission_token = admission.register_admission_source(
            "llm_engine", self.admission_snapshot
        )
        # per-engine series (keyed by the registry token): two engines
        # must not clobber each other's admission-depth gauge
        self._depth_tags = {"layer": "engine", "engine": str(self._admission_token)}
        # per-engine SLO latency sketches (deterministic fixed-boundary
        # quantiles, observability/sketch.py): fed from the engine's OWN
        # request timestamps, so TTFT/inter-token/queue-wait/e2e
        # percentiles exist even when the engine is driven directly
        # without a serve ingress (no trace riding the request). Written
        # only by the engine/request threads; snapshot readers tolerate a
        # torn single-counter read.
        self._sketches = {
            "ttft": LatencySketch(),
            "inter_token": LatencySketch(),
            "queue_wait": LatencySketch(),
            "e2e": LatencySketch(),
        }
        # bounded ring of recently terminated request summaries — the
        # flight recorder's raw material when the loop crashes
        self._finished_ring: deque = deque(maxlen=64)

        # slot state (host-side mirrors of the device arrays)
        self._slots: List[Optional[GenRequest]] = [None] * self.B
        self._last_tok = np.zeros(self.B, np.int32)
        self._pos = np.zeros(self.B, np.int32)
        self._temps = np.zeros(self.B, np.float32)
        self._active = np.zeros(self.B, bool)
        # paged state: per-slot block tables (host mirror of the device
        # int32[B, M] array), pages held per slot, and slots reserved by a
        # request whose chunked prefill is still in flight (the slot is
        # taken but must not receive decode tokens yet)
        self._block_tables = np.zeros((self.B, self.max_blocks_per_slot), np.int32)
        self._slot_blocks: List[List[int]] = [[] for _ in range(self.B)]
        self._reserved = np.zeros(self.B, bool)
        self._prefilling: List[GenRequest] = []
        # head-of-line request popped from the fair queue but waiting for
        # pages: held (not re-pushed — that would break fair ordering)
        # until release paths free enough blocks
        self._held_req: Optional[GenRequest] = None
        self._prefill_chunk_count = 0
        # disaggregated serving: staged exports parked by migration id
        # (the extracted block arrays outlive the prefill request's pool
        # pages — those retire into the prefix cache at export) and the
        # in/out migration counters surfaced by stats()/rt llm
        self._staged: Dict[str, dict] = {}
        self.num_migrations_out = 0
        self.num_migrations_in = 0
        metric_defs.LLM_KV_BLOCK_POOL_SIZE.set(
            self._allocator.capacity if self._allocator is not None else 0,
            self._depth_tags,
        )
        metric_defs.LLM_KV_BLOCKS_IN_USE.set(0, self._depth_tags)
        metric_defs.LLM_KV_BLOCKS_SHARED.set(0, self._depth_tags)
        metric_defs.LLM_PREFIX_CACHE_BLOCKS.set(0, self._depth_tags)

        self._reset_cache()
        self._key = jax.random.key(np.random.randint(0, 2**31 - 1))

        cfg_ = cfg
        layer_scales = self._layer_scales
        kv_spec = self._kv_spec
        # under a mesh the einsum path partitions via GSPMD; the Pallas
        # kernel paths stay for the single-device engine
        use_kernel = None if mesh is None else False
        prefill_kernel = mesh is None and jax.default_backend() == "tpu"

        @jax.jit
        def _prefill_one(params, tokens, length):
            """tokens [1, Tb] (bucket-padded); length is traced so all
            prompts in a bucket share ONE compile. Returns (logits [V],
            cache row)."""
            row = init_cache(cfg_, 1, self.S)
            if kv_spec is not None:
                row = {k: jax.lax.with_sharding_constraint(v, kv_spec) for k, v in row.items()}
            positions = jnp.arange(tokens.shape[1])[None, :]
            logits, row = forward_with_cache(
                cfg_, params, row, tokens, positions,
                layer_scales=layer_scales, use_decode_kernel=use_kernel,
                use_prefill_kernel=prefill_kernel,  # positions start at 0 here
            )
            return jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0, keepdims=False), row

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _insert(cache, row, slot):
            out = {}
            for kk in ("k", "v"):
                out[kk] = jax.vmap(
                    lambda c, r: jax.lax.dynamic_update_slice(c, r, (slot, 0, 0, 0))
                )(cache[kk], row[kk])
            return out

        top_k_, top_p_ = self.top_k, self.top_p

        def _sample_impl(key, logits, temps):
            """Per-slot temperature; temp <= 0 means greedy."""
            greedy = temps <= 0.0
            t = jnp.where(greedy, 1.0, temps)
            scaled = filter_top_k_top_p(logits / t[:, None], top_k_, top_p_)
            keys = jax.random.split(key, logits.shape[0])
            sampled = jax.vmap(jax.random.categorical)(keys, scaled)
            return jnp.where(greedy, jnp.argmax(logits, -1), sampled).astype(jnp.int32)

        _sample = jax.jit(_sample_impl)

        # the decode program: K sequential decode+sample steps inside ONE
        # jitted lax.scan (K = decode_chunk; 1 = classic per-token
        # stepping), so the host pays one dispatch/readback round trip per
        # K tokens. One key split per generated token.  The cache is
        # donated: the engine holds the only reference and reassigns, so
        # XLA updates the [L,B,Hkv,S,Dh] buffers in place.
        K_chunk = self.decode_chunk

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode_k(params, cache, toks, pos, temps, key):
            def body(carry, _):
                cache, toks, pos, key = carry
                logits, cache = decode_step(
                    cfg_, params, cache, toks, pos,
                    layer_scales=layer_scales, use_decode_kernel=use_kernel,
                )
                key, sub = jax.random.split(key)
                nxt = _sample_impl(sub, logits, temps)
                return (cache, nxt, pos + 1, key), nxt

            (cache, _, _, key), toks_k = jax.lax.scan(
                body, (cache, toks, pos, key), None, length=K_chunk
            )
            return jnp.swapaxes(toks_k, 0, 1), cache, key  # [B, K]

        self._decode_k = _decode_k
        self._prefill_one = _prefill_one
        self._insert = _insert
        self._sample = _sample

        if self.cache_kind == "paged":

            @functools.partial(jax.jit, donate_argnums=(1,))
            def _prefill_chunk(params, cache, toks, bt, start, length):
                """toks [1, C] chunk-padded; bt [1, M]; start/length traced,
                so every chunk of every prompt at width C shares ONE
                compile. Writes K/V for the chunk's ``length`` real tokens
                through the block table and returns the last real token's
                logits [V] (only the final chunk's are consumed)."""
                C = toks.shape[1]
                positions = start + jnp.arange(C)[None, :]
                valid = (jnp.arange(C) < length)[None, :]
                logits, cache = paged_forward_with_cache(
                    cfg_, params, cache, bt, toks, positions,
                    valid=valid, layer_scales=layer_scales, use_decode_kernel=False,
                )
                last = jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0, keepdims=False)
                return last, cache

            @functools.partial(jax.jit, donate_argnums=(1,))
            def _decode_k_paged(params, cache, toks, pos, temps, key, bt):
                def body(carry, _):
                    cache, toks, pos, key = carry
                    logits, cache = paged_decode_step(
                        cfg_, params, cache, toks, pos, bt,
                        layer_scales=layer_scales, use_decode_kernel=use_kernel,
                    )
                    key, sub = jax.random.split(key)
                    nxt = _sample_impl(sub, logits, temps)
                    return (cache, nxt, pos + 1, key), nxt

                (cache, _, _, key), toks_k = jax.lax.scan(
                    body, (cache, toks, pos, key), None, length=K_chunk
                )
                return jnp.swapaxes(toks_k, 0, 1), cache, key  # [B, K]

            # copy-on-write primitive (models/generation.copy_paged_page):
            # donated so XLA copies the page in place in the pool buffers
            self._copy_page = jax.jit(copy_paged_page, donate_argnums=(0,))

            @functools.partial(jax.jit, donate_argnums=(0,))
            def _write_blocks(cache, kvs, pages):
                """Land a migrated block set into the pool in ONE donated
                scatter: ``kvs`` is ``[N, 2, L, block_size, Hkv, Dh]`` (k
                then v per block), ``pages`` the destination page of each.
                Per-block writes cost a dispatch each — 24 blocks of a
                long prompt stall the engine loop ~10ms on the bench box.
                Callers bucket-pad N by repeating the last (block, page)
                pair (identical bytes to the same page, so the duplicate
                scatter indices stay idempotent), keeping the compile
                count at O(log blocks), not one per block count."""
                out = {}
                for i, kk in enumerate(("k", "v")):
                    out[kk] = cache[kk].at[:, pages].set(
                        jnp.swapaxes(kvs[:, i], 0, 1)
                    )
                return out

            self._write_blocks = _write_blocks
            self._prefill_chunk = _prefill_chunk
            self._decode_k_paged = _decode_k_paged

        self._thread = threading.Thread(target=self._loop, daemon=True, name="llm-engine")
        self._thread.start()

    # -- public API ---------------------------------------------------------
    def submit(
        self,
        prompt: List[int],
        *,
        max_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        tenant: Optional[str] = None,
        deadline_ts: Optional[float] = None,
        _stream_queue=None,
    ) -> Future:
        """Enqueue one request; resolves to the generated token-id list.

        ``tenant`` (default: the request-context tenant id set by the
        ingress) keys weighted fair queuing; ``deadline_ts`` (default: the
        PR-8 deadline riding the request context) sheds on arrival when
        already expired.  Raises OverloadedError when the bounded waiting
        queue (count or prefill-token budget) is full."""
        return self._submit_req(
            prompt,
            max_tokens=max_tokens,
            temperature=temperature,
            eos_id=eos_id,
            tenant=tenant,
            deadline_ts=deadline_ts,
            _stream_queue=_stream_queue,
        ).future

    def _submit_req(
        self,
        prompt: List[int],
        *,
        max_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        tenant: Optional[str] = None,
        deadline_ts: Optional[float] = None,
        _stream_queue=None,
        _export_mig_id: Optional[str] = None,
        _import_ticket: Optional[dict] = None,
        _import_arrays: Optional[Dict[int, Any]] = None,
    ) -> GenRequest:
        if self._stop:
            raise RuntimeError("LLMEngine is shut down")
        if not prompt:
            raise ValueError("empty prompt")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        if len(prompt) + max_tokens > self.S:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens ({max_tokens}) exceeds "
                f"engine max_seq_len {self.S}"
            )
        if self._allocator is not None:
            # never-fits contract (same as max_queued_prefill_tokens below):
            # a request needing more pages than the POOL holds can never be
            # admitted — that is a config/input error at submit, not a
            # retry-after-able overload and not a failure deep in prefill
            needed = -(-(len(prompt) + max_tokens - 1) // self.kv_block_size)
            if needed > self._allocator.capacity:
                raise ValueError(
                    f"prompt ({len(prompt)}) + max_tokens ({max_tokens}) needs "
                    f"{needed} KV blocks but the pool only holds "
                    f"{self._allocator.capacity} and would never be admitted"
                )
        if self._max_queued_tokens and len(prompt) > self._max_queued_tokens:
            # a prompt that ALONE exceeds the budget can never be admitted:
            # that is a config/input error, not a retry-after-able overload
            raise ValueError(
                f"prompt ({len(prompt)} tokens) exceeds the engine's "
                f"max_queued_prefill_tokens budget ({self._max_queued_tokens}) "
                "and would never be admitted"
            )
        if tenant is None:
            tenant = current_tenant()
        if deadline_ts is None:
            deadline_ts = current_deadline_ts()
        # the lifecycle trace rode proxy -> router -> replica context to
        # get here; stamp the engine-submit boundary before any shed so a
        # shed request still shows where it died
        trace = current_request_trace()
        if trace is not None:
            trace.mark("engine_submit")
        if deadline_ts is not None and time.time() >= deadline_ts:
            # shed-on-arrival: the deadline already expired — admitting
            # would burn prefill + a decode slot on an answer nobody can
            # use.  The typed signal is the deadline error, not 429.
            with self._lock:  # += races the other shed paths' increments
                self.num_shed += 1
            admission.record_shed("engine", "deadline_expired")
            raise DeadlineExceededError("llm_request", "engine_admission", 0.0)
        with self._lock:
            depth = len(self._queue)
            if self._max_queued and depth >= self._max_queued:
                self.num_shed += 1
                raise admission.shed(
                    "engine", "queue_full",
                    message=(
                        f"engine waiting queue at its {self._max_queued}-"
                        f"request bound"
                    ),
                )
            if (
                self._max_queued_tokens
                and self._queued_tokens + len(prompt) > self._max_queued_tokens
            ):
                self.num_shed += 1
                raise admission.shed(
                    "engine", "token_budget",
                    message=(
                        f"queued prefill tokens {self._queued_tokens} + "
                        f"{len(prompt)} exceed the "
                        f"{self._max_queued_tokens}-token budget"
                    ),
                )
            req = GenRequest(
                list(prompt), max_tokens, temperature, eos_id,
                stream_queue=_stream_queue, tenant=tenant,
                deadline_ts=deadline_ts, trace=trace,
            )
            req.export_mig_id = _export_mig_id
            req.import_ticket = _import_ticket
            req.import_arrays = _import_arrays
            req.t_submit = time.perf_counter()
            self._queue.push(req, tenant)
            self._queued_tokens += len(prompt)
            depth += 1
        metric_defs.ADMISSION_QUEUE_DEPTH.set(depth, self._depth_tags)
        metric_defs.TENANT_ADMISSIONS.inc(tags=admission.tenant_tags(tenant))
        self._wake.set()
        return req

    def generate(self, prompt: List[int], **kw) -> List[int]:
        return self.submit(prompt, **kw).result()

    def submit_stream(self, prompt: List[int], *, token_timeout_s: float = 120.0, **kw):
        """Per-token streaming: returns an iterator yielding token ids as
        they are sampled (the continuous-batching analog of the runtime's
        ObjectRefGenerator). Validation errors raise HERE, not mid-stream.
        The iterator ends at eos/max_tokens; engine errors re-raise at the
        end of iteration; a stalled engine raises after ``token_timeout_s``
        without a token (so consumers never block forever)."""
        import queue as _queue

        q: "_queue.Queue" = _queue.Queue()
        req = self._submit_req(prompt, _stream_queue=q, **kw)
        return _TokenStream(self._stream_iter(req, q, token_timeout_s), req, self)

    def _stream_iter(self, req: GenRequest, q, token_timeout_s: float = 120.0):
        """Generator draining ``req``'s stream queue until ``_STREAM_END``
        (shared by submit_stream and the disagg adopt-stream path)."""
        import queue as _queue

        fut = req.future
        while True:
            try:
                tok = q.get(timeout=token_timeout_s)
            except _queue.Empty:
                raise RuntimeError(
                    f"no token for {token_timeout_s}s — engine stalled or overloaded"
                ) from None
            if tok is _STREAM_END:
                exc = fut.exception() if fut.done() else None
                if exc is not None:
                    raise exc
                return
            yield tok

    def _abandon_stream(self, req: GenRequest) -> None:
        """Consumer gone: if the request is still WAITING, drop it from the
        queue NOW (its count + prefill tokens stop holding the bounded
        budget against live traffic); if it holds a decode slot, flag it
        for eviction at the next engine-loop tick."""
        req.cancelled = True
        with self._lock:
            removed = self._queue.remove(req)
            if removed:
                self._queued_tokens -= len(req.prompt)
                self.num_shed += 1  # under the lock: += races other shed paths
            depth = len(self._queue)
        if removed:
            metric_defs.ADMISSION_QUEUE_DEPTH.set(depth, self._depth_tags)
            admission.record_shed("engine", "disconnect")
            self._record_done(req, "disconnect", "stream abandoned while queued")
            if not req.future.done():
                req.future.set_exception(
                    RuntimeError("stream consumer disconnected before admission")
                )
        else:
            self._wake.set()

    # -- disaggregated prefill/decode (serve/disagg.py) ---------------------
    def prefill_export(
        self,
        prompt: List[int],
        *,
        mig_id: str,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        tenant: Optional[str] = None,
        deadline_ts: Optional[float] = None,
    ) -> Future:
        """Prefill-pool entry point: chunked-prefill ``prompt`` into local
        paged KV, sample the first token, stage the block set under
        ``mig_id`` and resolve the future with the migration ticket
        (header-only — zero KV payload bytes).  The request reserves only
        the prompt's pages (``max_tokens=1``): decode never runs here."""
        if self.cache_kind != "paged":
            raise ValueError("prefill_export requires the paged KV cache")
        return self._submit_req(
            prompt, max_tokens=1, temperature=temperature, eos_id=eos_id,
            tenant=tenant, deadline_ts=deadline_ts, _export_mig_id=mig_id,
        ).future

    def adopt_migration(
        self,
        ticket: dict,
        arrays: Dict[int, Any],
        *,
        max_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        tenant: Optional[str] = None,
        deadline_ts: Optional[float] = None,
        _stream_queue=None,
    ) -> GenRequest:
        """Decode-pool entry point: join the continuous batch from a
        migrated block set.  ``arrays`` maps prompt block index -> the
        pulled ``[2, L, block_size, Hkv, Dh]`` stack (the caller pulls on
        its own thread — only the engine loop may touch the cache); block
        indices already covered by this replica's prefix cache may be
        omitted.  Admission, block budget, COW and prefix-cache semantics
        are the normal paged path; only prefill compute is skipped."""
        if self.cache_kind != "paged":
            raise ValueError("adopt_migration requires the paged KV cache")
        return self._submit_req(
            list(ticket["prompt"]), max_tokens=max_tokens,
            temperature=temperature, eos_id=eos_id, tenant=tenant,
            deadline_ts=deadline_ts, _stream_queue=_stream_queue,
            _import_ticket=dict(ticket),
            _import_arrays=dict(arrays),
        )

    def peek_prefix_match(self, prompt: List[int]) -> int:
        """Longest cached prefix (tokens) of ``prompt`` in THIS replica's
        prefix cache — the decode side probes before pulling so a warm
        prefix short-circuits re-migration of shared-prefix blocks.
        Advisory: admission re-matches, and a shrink in between surfaces
        as a typed migration error (the ladder re-prefills)."""
        if self._prefix is None:
            return 0
        with self._lock:
            _, matched = self._prefix.match(prompt)
        return matched

    def kv_free_blocks(self) -> int:
        """Free pages right now — the decode-pool routing signal."""
        alloc = self._allocator
        if alloc is None:
            return 0
        with self._lock:
            return alloc.free_blocks

    def release_migration(self, mig_id: str) -> bool:
        """Drop a staged export: forget the arrays and unregister the
        host-fallback source.  Device-plane offers have no cancel API —
        unpulled ones expire via the transfer server's staging TTL (a
        documented device_plane caveat).  Idempotent; True if the staging
        existed.  The prefill-side POOL pages were already retired into
        the prefix cache at export, so this never touches the pool —
        exactly-once freeing is the export path's invariant."""
        with self._lock:
            entry = self._staged.pop(mig_id, None)
        if entry is None:
            return False
        from ray_tpu.runtime import data_plane

        data_plane.unregister_kv_block_source(mig_id)
        return True

    def fetch_staged_block(self, mig_id: str, block_idx: int):
        """One staged block.  Returns the staged device array as-is: the
        in-process rung adopts it without a host round-trip, and the
        data-plane ``kv_pull`` op host-converts it only when actually
        serving a remote pull."""
        with self._lock:
            entry = self._staged.get(mig_id)
        if entry is None:
            raise KeyError(f"no staged migration {mig_id!r}")
        return entry["arrays"][block_idx]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            alloc = self._allocator
            return {
                "role": self.role,
                "migrations_out": self.num_migrations_out,
                "migrations_in": self.num_migrations_in,
                "staged_migrations": len(self._staged),
                "active_slots": int(self._active.sum()),
                "max_batch_size": self.B,
                "queued": len(self._queue),
                "queued_prefill_tokens": self._queued_tokens,
                "prefill_forwards": self._prefill_count,
                "slots_evicted": self.num_slots_evicted,
                "shed": self.num_shed,
                "cache_kind": self.cache_kind,
                "kv_block_size": self.kv_block_size if alloc is not None else 0,
                "kv_block_pool_size": alloc.capacity if alloc is not None else 0,
                "kv_blocks_in_use": alloc.used_blocks if alloc is not None else 0,
                "kv_blocks_shared": alloc.shared_blocks if alloc is not None else 0,
                "prefilling": len(self._prefilling),
                "prefill_chunks": self._prefill_chunk_count,
                "prefix_cache_enabled": self._prefix is not None,
                "prefix_cache_blocks": len(self._prefix) if self._prefix is not None else 0,
                "prefix_cache_hits": self._prefix_results["hit"],
                "prefix_cache_partial": self._prefix_results["partial"],
                "prefix_cache_misses": self._prefix_results["miss"],
                "prefix_tokens_reused": self._prefix_tokens_reused,
                "prefix_evictions": self._prefix.evictions if self._prefix is not None else 0,
                "cow_copies": self._cow_count,
            }

    def admission_snapshot(self) -> Dict[str, Any]:
        """Bounds + depths for GET /api/overload (admission source)."""
        with self._lock:
            alloc = self._allocator
            pool = alloc.capacity if alloc is not None else 0
            in_use = alloc.used_blocks if alloc is not None else 0
            probes = sum(self._prefix_results.values())
            useful = self._prefix_results["hit"] + self._prefix_results["partial"]
            return {
                "layer": "engine",
                "role": self.role,
                "migrations_out": self.num_migrations_out,
                "migrations_in": self.num_migrations_in,
                "staged_migrations": len(self._staged),
                "queued": len(self._queue),
                "queue_bound": self._max_queued,
                "queued_prefill_tokens": self._queued_tokens,
                "token_budget": self._max_queued_tokens,
                "active_slots": int(self._active.sum()),
                "slots": self.B,
                "by_tenant": self._queue.depth_by_tenant(),
                "slots_evicted": self.num_slots_evicted,
                "shed": self.num_shed,
                "cache_kind": self.cache_kind,
                "kv_block_size": self.kv_block_size if alloc is not None else 0,
                "kv_block_pool_size": pool,
                "kv_blocks_in_use": in_use,
                "kv_blocks_shared": alloc.shared_blocks if alloc is not None else 0,
                "kv_block_occupancy": (in_use / pool) if pool else 0.0,
                "prefilling": len(self._prefilling),
                "prefill_chunks": self._prefill_chunk_count,
                "waiting_for_blocks": 1 if self._held_req is not None else 0,
                "prefix_cache_enabled": self._prefix is not None,
                "prefix_cache_blocks": len(self._prefix) if self._prefix is not None else 0,
                "prefix_hit_rate": (useful / probes) if probes else 0.0,
                "prefix_tokens_reused": self._prefix_tokens_reused,
                "prefix_evictions": self._prefix.evictions if self._prefix is not None else 0,
                # SLO percentiles from the engine-side latency sketches
                # (ttft / inter_token / queue_wait / e2e, seconds)
                "latency": {
                    name: sk.percentiles() for name, sk in self._sketches.items()
                },
            }

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5)
        admission.unregister_admission_source(self._admission_token)
        # zero this engine's gauge series; the freed token (and thus the
        # series label) is reused by the next engine
        metric_defs.ADMISSION_QUEUE_DEPTH.set(0, self._depth_tags)
        if self._allocator is not None:
            metric_defs.LLM_KV_BLOCKS_IN_USE.set(0, self._depth_tags)
            metric_defs.LLM_KV_BLOCK_POOL_SIZE.set(0, self._depth_tags)
            metric_defs.LLM_KV_BLOCKS_SHARED.set(0, self._depth_tags)
            metric_defs.LLM_PREFIX_CACHE_BLOCKS.set(0, self._depth_tags)
        with self._lock:
            pending = [r for r in self._queue.items() if not r.future.done()]
            pending += [r for r in self._slots if r is not None and not r.future.done()]
            pending += [r for r in self._prefilling if not r.future.done()]
            self._prefilling.clear()
            if self._held_req is not None:
                if not self._held_req.future.done():
                    pending.append(self._held_req)
                self._held_req = None
            self._queue.drain()
            self._queued_tokens = 0
            staged = list(self._staged)
            self._staged.clear()
        if staged:
            from ray_tpu.runtime import data_plane

            for mig_id in staged:
                data_plane.unregister_kv_block_source(mig_id)
        for r in pending:
            r.future.set_exception(RuntimeError("LLMEngine shut down"))
            if r.stream_queue is not None:
                r.stream_queue.put(_STREAM_END)

    def flush_prefix_cache(self) -> int:
        """Evict every prefix-cache entry not currently shared into a live
        request and return the number of pages freed.  Ops hook — also the
        leak-check primitive: on a quiesced engine, ``kv_blocks_in_use``
        equals ``prefix_cache_blocks`` and a flush takes both to zero."""
        if self._prefix is None:
            return 0
        with self._lock:
            pages = self._prefix.evict(len(self._prefix), self._evictable)
            if pages:
                self._allocator.free(pages)
            gauges = self._pool_gauges_locked()
        if pages:
            metric_defs.LLM_PREFIX_EVICTIONS.inc(len(pages))
        self._publish_pool_gauges(*gauges)
        return len(pages)

    def _evictable(self, page: int) -> bool:
        """An eviction may only take pages whose sole reference is the
        cache's own — refcount 1 means no live block table names the page.
        Caller holds ``self._lock``."""
        return self._allocator.refcount(page) == 1

    def _pool_gauges_locked(self):
        """(in_use, shared, cache_blocks) snapshot; caller holds the lock."""
        alloc = self._allocator
        return (
            alloc.used_blocks if alloc is not None else 0,
            alloc.shared_blocks if alloc is not None else 0,
            len(self._prefix) if self._prefix is not None else 0,
        )

    def _publish_pool_gauges(self, in_use: int, shared: int, cache_blocks: int) -> None:
        metric_defs.LLM_KV_BLOCKS_IN_USE.set(in_use, self._depth_tags)
        metric_defs.LLM_KV_BLOCKS_SHARED.set(shared, self._depth_tags)
        metric_defs.LLM_PREFIX_CACHE_BLOCKS.set(cache_blocks, self._depth_tags)

    # -- request-scope latency bookkeeping ----------------------------------
    def _note_first_token(self, req: GenRequest) -> None:
        """TTFT boundary: the first sampled token leaves the engine."""
        now = time.perf_counter()
        req.t_first = req.t_last_tok = now
        if req.t_submit:
            ttft = now - req.t_submit
            self._sketches["ttft"].observe(ttft)
            metric_defs.LLM_TTFT.observe(ttft, self._depth_tags)
        if req.trace is not None:
            req.trace.note_token(0.0)  # marks first_token on the trace

    def _note_next_token(self, req: GenRequest) -> None:
        """Inter-token gap: one decode token after the first."""
        now = time.perf_counter()
        gap = now - req.t_last_tok
        req.t_last_tok = now
        self._sketches["inter_token"].observe(gap)
        metric_defs.LLM_INTER_TOKEN.observe(gap, self._depth_tags)
        if req.trace is not None:
            req.trace.note_token(gap)

    def _note_stall(self) -> None:
        """A prefill forward just stalled every running decode slot: count
        the stall on each stalled request's trace (the decoding requests
        experience the bubble, not the prefilling one)."""
        # rt-lint: disable=lock-discipline -- engine-thread-owned: _slots
        # mutations all run on this same engine loop thread (see _step)
        for r in self._slots:
            if r is not None and r.trace is not None:
                r.trace.note_stall()

    def _record_done(self, req: GenRequest, outcome: str, detail: str = "") -> None:
        """Terminal bookkeeping shared by every exit path: feed the e2e
        sketch (engine-side view: submit -> terminal, successful finishes
        only) and push a summary onto the bounded ring the flight recorder
        snapshots. Abnormal terminals claim the trace outcome HERE so the
        proxy's generic mapping (first-wins) cannot mislabel them."""
        now = time.perf_counter()
        e2e = (now - req.t_submit) if req.t_submit else 0.0
        if outcome == "finish":
            self._sketches["e2e"].observe(e2e)
        elif req.trace is not None:
            req.trace.set_outcome(outcome, detail or f"engine:{outcome}")
        self._finished_ring.append({
            "outcome": outcome,
            "detail": detail,
            "tenant": req.tenant or "",
            "prompt_tokens": len(req.prompt),
            "generated": len(req.generated),
            "e2e_ms": round(e2e * 1000.0, 3),
            "ttft_ms": (
                round((req.t_first - req.t_submit) * 1000.0, 3)
                if req.t_first and req.t_submit else None
            ),
        })

    # -- engine loop --------------------------------------------------------
    def _admit(self) -> None:
        if self.cache_kind == "paged":
            self._admit_paged()
        else:
            self._admit_dense()

    def _pop_admissible(self, *, need_free_slot: bool = True):
        """Shared admit-loop head: pop (or resume) the next runnable request.

        Returns ``(req, free_slots)`` with shed-on-pop filtering applied, or
        ``None`` when there is nothing admissible right now. A paged engine's
        head-of-line request waiting for blocks lives in ``self._held_req``
        and is resumed here (never re-pushed: re-pushing would re-bill its
        stride and let later arrivals overtake the weighted-fair order).
        """
        while True:
            with self._lock:
                free = [
                    i for i in range(self.B)
                    if not self._active[i] and not self._reserved[i]
                ]
                if need_free_slot and not free:
                    return None
                if self._held_req is not None:
                    req = self._held_req
                    self._held_req = None
                elif len(self._queue):
                    req = self._queue.pop()  # weighted fair order across tenants
                    self._queued_tokens -= len(req.prompt)
                else:
                    return None
                depth = len(self._queue)
            metric_defs.ADMISSION_QUEUE_DEPTH.set(depth, self._depth_tags)
            if req.cancelled:
                # abandoned while waiting: never prefill it
                with self._lock:  # += races the request-thread shed paths
                    self.num_shed += 1
                admission.record_shed("engine", "disconnect")
                self._record_done(
                    req, "disconnect", "stream consumer gone before admission"
                )
                if not req.future.done():
                    req.future.set_exception(
                        RuntimeError("stream consumer disconnected before admission")
                    )
                continue
            if req.deadline_ts is not None and time.time() >= req.deadline_ts:
                # expired while queued: shed instead of occupying a slot
                with self._lock:  # += races the request-thread shed paths
                    self.num_shed += 1
                admission.record_shed("engine", "deadline_expired")
                self._record_done(req, "deadline", "deadline expired while queued")
                if not req.future.done():
                    req.future.set_exception(
                        DeadlineExceededError("llm_request", "engine_queue", 0.0)
                    )
                if req.stream_queue is not None:
                    req.stream_queue.put(_STREAM_END)
                continue
            if not req.wfq_popped:
                # queue-wait ends at the FIRST pop; a held head-of-line
                # request resumed from _held_req is in kv_block_wait, not
                # queue time, and must not re-observe
                req.wfq_popped = True
                if req.t_submit:
                    self._sketches["queue_wait"].observe(
                        time.perf_counter() - req.t_submit
                    )
                if req.trace is not None:
                    req.trace.mark("wfq_pop")
            return req, free

    def _admit_dense(self) -> None:
        while True:
            popped = self._pop_admissible()
            if popped is None:
                return
            req, free = popped
            slot = free[0]
            if req.trace is not None:
                # dense admission is immediate: no kv_block_wait phase
                req.trace.mark("admitted")
            try:
                tp = len(req.prompt)
                bucket = _bucket(tp, cap=self.S)
                toks = np.zeros((1, bucket), np.int32)
                toks[0, :tp] = req.prompt
                stalled = bool(self._active.any())
                t0 = time.perf_counter()
                logits, row = self._prefill_one(self.params, jnp.asarray(toks), jnp.int32(tp))
                jax.block_until_ready(logits)
                if stalled:
                    # decode slots sat idle for this whole one-shot prefill
                    metric_defs.LLM_DECODE_STALL.observe(time.perf_counter() - t0)
                    self._note_stall()
                with self._lock:  # stats() reads this under the lock
                    self._prefill_count += 1
                self._cache = self._insert(self._cache, row, slot)
                # first output token comes straight from the prefill logits
                self._key, sub = jax.random.split(self._key)
                tok0 = int(
                    self._sample(
                        sub, logits[None, :], jnp.asarray([req.temperature], jnp.float32)
                    )[0]
                )
            except BaseException as exc:  # noqa: BLE001
                # the popped request is in neither queue nor slots — fail it
                # HERE or its caller hangs forever
                self._fail_admit(req, exc)
                continue
            req.slot = slot
            req.generated = [tok0]
            self._note_first_token(req)
            req.emit(tok0)
            with self._lock:
                self._slots[slot] = req
                self._active[slot] = True
                self._last_tok[slot] = tok0
                self._pos[slot] = tp
                self._temps[slot] = req.temperature
            if self._maybe_finish(req, tok0):
                continue

    def _admit_paged(self) -> None:
        """Block-aware admission: reserve the request's whole page budget up
        front (``ceil((prompt + max_tokens - 1) / block_size)`` — the last
        written position is ``prompt + max_tokens - 2``), so an admitted
        request can never hit a mid-decode pool OOM and nothing is ever
        preempted. Prefill itself runs later, chunk by chunk, from
        ``_prefill_tick`` so decode steps interleave with long prompts.

        With the prefix cache, the longest cached prefix of the prompt is
        ``share()``d straight into the block table (zero prefill compute for
        the hit region — chunked prefill starts at the first uncached token)
        and only the uncached suffix reserves fresh pages. A full-prompt hit
        still recomputes the LAST prompt token (its logits seed sampling),
        and that write would land in the final matched block — a shared
        page — so that block is copy-on-write: the request gets a fresh
        page populated by a device page copy instead of a share."""
        bs = self.kv_block_size
        while True:
            popped = self._pop_admissible()
            if popped is None:
                return
            req, free = popped
            tp = len(req.prompt)
            total = -(-(tp + req.max_tokens - 1) // bs)
            with self._lock:
                pages: List[int] = []
                matched = 0
                if self._prefix is not None:
                    pages, matched = self._prefix.match(req.prompt)
                cow_src = -1
                if matched == tp:
                    # full-prompt hit: the tail block must be writable
                    cow_src = pages.pop()
                    matched -= bs
                # pin the hit region (and the COW source) FIRST: the
                # eviction sweep below must never free a page we matched
                pins = pages + ([cow_src] if cow_src >= 0 else [])
                if pins:
                    self._allocator.share(pins)
                needed = total - len(pages)
                short = needed - self._allocator.free_blocks
                evicted_n = 0
                if short > 0 and self._prefix is not None:
                    # pool short: LRU-sweep unreferenced cached leaves
                    # before holding (and long before admission sheds)
                    evicted = self._prefix.evict(short, self._evictable)
                    if evicted:
                        self._allocator.free(evicted)
                        evicted_n = len(evicted)
                if needed > self._allocator.free_blocks:
                    # head-of-line waits for release paths to return pages;
                    # skipping it would starve big requests behind small
                    # ones. Drop the pins — it re-probes the cache on wake.
                    if pins:
                        self._allocator.free(pins)
                    self._held_req = req
                    if evicted_n:
                        metric_defs.LLM_PREFIX_EVICTIONS.inc(evicted_n)
                    return
                blocks = pages + self._allocator.alloc(needed)
                slot = free[0]
                self._reserved[slot] = True
                self._slot_blocks[slot] = blocks
                self._block_tables[slot, :] = 0
                self._block_tables[slot, : len(blocks)] = blocks
                hit_tokens = matched + (bs if cow_src >= 0 else 0)
                if self._prefix is not None:
                    fb = (tp // bs) * bs  # the matchable (full-block) region
                    result = (
                        ("hit" if hit_tokens == fb else "partial")
                        if hit_tokens > 0
                        else "miss"
                    )
                    self._prefix_results[result] += 1
                    self._prefix_tokens_reused += (
                        tp - 1 if cow_src >= 0 else matched
                    )
                gauges = self._pool_gauges_locked()
            if evicted_n:
                metric_defs.LLM_PREFIX_EVICTIONS.inc(evicted_n)
            self._publish_pool_gauges(*gauges)
            if self._prefix is not None:
                metric_defs.LLM_PREFIX_CACHE_HITS.inc(tags=_PREFIX_RESULT_TAGS[result])
            req.slot = slot
            if req.trace is not None:
                # pages reserved: kv_block_wait (wfq_pop -> here) is over
                req.trace.mark("admitted")
            # chunked prefill resumes at the first token whose KV is not
            # already in the table (tp - 1 for a full hit: one recompute)
            req.prefill_pos = matched
            if cow_src >= 0:
                try:
                    dst = blocks[len(pages)]  # the fresh page for the tail block
                    self._cache = self._copy_page(
                        self._cache, jnp.int32(cow_src), jnp.int32(dst)
                    )
                    with self._lock:
                        self._allocator.free([cow_src])  # drop the copy pin
                        self._cow_count += 1
                except BaseException as exc:  # noqa: BLE001
                    with self._lock:
                        self._allocator.free([cow_src])
                    self._fail_admit(req, exc)
                    continue
                req.prefill_pos = tp - 1
            if req.import_arrays is not None:
                # migrated request: blocks land from the producer's staged
                # arrays (or this replica's own prefix cache) — no prefill.
                # One adoption per admission pass: landing a block set is
                # the heaviest admission step, and a migration burst
                # draining in a single pass would stall the decode cadence
                # for every running stream (the loop re-admits next tick)
                self._adopt_admitted(req, had_cow=cow_src >= 0)
                return
            with self._lock:
                self._prefilling.append(req)

    def _finish_prefill(self, req: GenRequest, logits) -> None:
        """Prompt is fully in the paged cache: sample the first token and
        hand the slot to the decode batch (or, for an export request,
        stage the block set for migration instead)."""
        tp = len(req.prompt)
        self._key, sub = jax.random.split(self._key)
        tok0 = int(
            self._sample(
                sub, logits[None, :], jnp.asarray([req.temperature], jnp.float32)
            )[0]
        )
        if req.export_mig_id is not None:
            self._export_staged(req, tok0)
            return
        req.generated = [tok0]
        self._note_first_token(req)
        req.emit(tok0)
        with self._lock:
            slot = req.slot
            self._slots[slot] = req
            self._active[slot] = True
            self._reserved[slot] = False
            self._last_tok[slot] = tok0
            self._pos[slot] = tp
            self._temps[slot] = req.temperature
        self._maybe_finish(req, tok0)

    def _adopt_admitted(self, req: GenRequest, *, had_cow: bool) -> None:
        """Activate an admitted IMPORT request: write the pulled block
        arrays into its freshly allocated pages (runs on the engine loop —
        the only thread allowed to touch the donated cache), then join the
        decode batch at position ``len(prompt)`` with the producer's first
        token.  A warm local prefix covers its blocks without any write
        (the re-migration short-circuit); a block neither cached nor
        pulled — the prefix shrank between the caller's probe and now —
        is the typed migration error, and the ladder re-prefills."""
        from ray_tpu.serve.disagg import KVMigrationError

        ticket = req.import_ticket or {}
        mig_id = ticket.get("mig_id", "?")
        tp = len(req.prompt)
        bs = self.kv_block_size
        n_blocks = -(-tp // bs)
        if not had_cow:
            # prefill_pos = matched tokens (a multiple of block_size);
            # with a full-hit COW every prompt position is already paged
            # in, so there is nothing to write at all
            writes = []
            for bidx in range(req.prefill_pos // bs, n_blocks):
                arr = (req.import_arrays or {}).get(bidx)
                if arr is None:
                    self._fail_admit(req, KVMigrationError(
                        mig_id, "pulled",
                        f"block {bidx} neither locally cached nor pulled "
                        f"(local prefix match shrank to {req.prefill_pos} "
                        "tokens after the probe)",
                    ))
                    return
                writes.append(
                    (arr, int(self._block_tables[req.slot, bidx]))
                )
            if writes:
                bucket = 1
                while bucket < len(writes):
                    bucket *= 2
                while len(writes) < bucket:  # idempotent scatter pad
                    writes.append(writes[-1])
                try:
                    # host-side stack: jnp.stack dispatches an expand_dims
                    # per block (~1.5ms for a long prompt's 32); np views
                    # of CPU-backend arrays memcpy in ~80µs, and the jit
                    # boundary ships one contiguous buffer
                    self._cache = self._write_blocks(
                        self._cache,
                        np.stack([np.asarray(a) for a, _ in writes]),
                        np.asarray([p for _, p in writes], np.int32),
                    )
                except BaseException as exc:  # noqa: BLE001
                    self._fail_admit(req, exc)
                    return
        tok0 = int(ticket.get("tok0", 0))
        req.generated = [tok0]
        now = time.perf_counter()
        req.t_first = req.t_last_tok = now
        if req.trace is not None:
            # the migration phase ends here: first_token was marked on the
            # prefill replica, decode gaps accrue on THIS one
            req.trace.mark("kv_migrate")
        req.emit(tok0)
        with self._lock:
            slot = req.slot
            self._slots[slot] = req
            self._active[slot] = True
            self._reserved[slot] = False
            self._last_tok[slot] = tok0
            self._pos[slot] = tp
            self._temps[slot] = req.temperature
            self.num_migrations_in += 1
        self._maybe_finish(req, tok0)

    def _export_staged(self, req: GenRequest, tok0: int) -> None:
        """Export terminal of a prefill-pool request: extract the prompt
        blocks as device-array copies, stage them for device-to-device
        pull under deterministic ``(request, block)`` uuids, register the
        host fallback source, retire the POOL pages into this replica's
        prefix cache (exactly-once: the staged copies, not the pages,
        migrate), and resolve the future with the header-only ticket."""
        from ray_tpu.runtime import data_plane, device_plane
        from ray_tpu.serve import disagg

        mig_id = req.export_mig_id
        tp = len(req.prompt)
        bs = self.kv_block_size
        n_blocks = -(-tp // bs)
        req.generated = [tok0]
        self._note_first_token(req)
        # engine-thread-only cache reads: jnp indexing materializes NEW
        # buffers, so the copies survive later donated steps
        arrays = []
        for bidx in range(n_blocks):
            page = int(self._block_tables[req.slot, bidx])
            arrays.append(
                jnp.stack([self._cache["k"][:, page], self._cache["v"][:, page]])
            )
        if arrays:
            jax.block_until_ready(arrays[-1])
        transfer_addr = device_plane.transfer_address()
        if transfer_addr is not None:
            for bidx, arr in enumerate(arrays):
                if not device_plane.offer_device_pull(
                    disagg.migration_uuid(mig_id, bidx), arr
                ):
                    # staging cap hit: advertise no device rung — offers
                    # already made are consumed or TTL-reaped
                    transfer_addr = None
                    break

        def _fetch(idx: int, _arrays=arrays):
            # device array as-is: the in-process rung adopts it zero-copy;
            # the data-plane kv_pull op host-converts only for remote pulls
            return _arrays[idx]

        data_plane.register_kv_block_source(mig_id, _fetch)
        evicted_n = 0
        with self._lock:
            # pool pages retire into the prefix cache NOW (cached tokens =
            # the prompt: tok0 was sampled, never written back) — the one
            # free of the migrated block set on this replica
            evicted_n = self._retire_blocks_locked(req)
            self._staged[mig_id] = {
                "arrays": arrays,
                "prompt": list(req.prompt),
                "n_blocks": n_blocks,
            }
            self.num_migrations_out += 1
            gauges = self._pool_gauges_locked()
        if evicted_n:
            metric_defs.LLM_PREFIX_EVICTIONS.inc(evicted_n)
        self._publish_pool_gauges(*gauges)
        ticket = disagg.make_ticket(
            mig_id,
            prompt=req.prompt,
            tok0=tok0,
            n_blocks=n_blocks,
            block_size=bs,
            block_shape=tuple(arrays[0].shape) if arrays else (0,),
            block_dtype=str(arrays[0].dtype) if arrays else "float32",
            transfer_addr=transfer_addr,
            data_addr=disagg.local_data_addr(),
            source=str(self._admission_token),
        )
        self._record_done(req, "finish", f"export {mig_id}")
        req.future.set_result(ticket)

    def _fail_admit(self, req: GenRequest, exc: BaseException) -> None:
        """A popped request is in neither queue nor slots — fail it HERE or
        its caller hangs forever; return any reserved pages to the pool."""
        self._record_done(req, "crash", f"prefill failed: {exc!r}")
        if not req.future.done():
            req.future.set_exception(RuntimeError(f"prefill failed: {exc!r}"))
        if req.stream_queue is not None:
            req.stream_queue.put(_STREAM_END)
        if self._allocator is not None and req.slot >= 0:
            with self._lock:
                self._release_blocks_locked(req.slot)
                gauges = self._pool_gauges_locked()
            self._publish_pool_gauges(*gauges)
        if self._cache["k"].is_deleted():
            # a donated insert/chunk consumed the cache then failed: the
            # shared cache is gone, taking every in-flight slot with it
            self._fail_inflight(RuntimeError(f"cache lost in failed prefill: {exc!r}"))
            self._reset_cache()

    def _release_blocks_locked(self, slot: int) -> None:
        """Drop a slot's page references (a request holds exactly ONE per
        block-table entry, shared or not, so every release path — finish,
        shed, evict, crash — is this same free). Caller holds ``self._lock``."""
        blocks = self._slot_blocks[slot]
        self._slot_blocks[slot] = []
        self._block_tables[slot, :] = 0
        self._reserved[slot] = False
        if blocks:
            self._allocator.free(blocks)

    def _retire_blocks_locked(self, req: GenRequest) -> int:
        """Finish path: publish the request's full KV blocks into the prefix
        cache (the request's reference TRANSFERS to the cache for newly
        adopted nodes) and free everything else. Returns the number of
        pages LRU-evicted to respect ``prefix_cache_max_blocks``. Caller
        holds ``self._lock``."""
        slot = req.slot
        blocks = self._slot_blocks[slot]
        self._slot_blocks[slot] = []
        self._block_tables[slot, :] = 0
        self._reserved[slot] = False
        if not blocks:
            return 0
        if self._prefix is None:
            self._allocator.free(blocks)
            return 0
        # the last sampled token was never written back to the KV cache;
        # every token before it was — cache exactly those full blocks
        cached = req.prompt + req.generated[:-1]
        adopted, evicted = self._prefix.insert(cached, blocks, self._evictable)
        if evicted:
            self._allocator.free(evicted)
        rest = [b for b in blocks if b not in adopted]
        if rest:
            self._allocator.free(rest)
        return len(evicted)

    def _cow_shared_writes(self, slot: int, start: int, n: int) -> None:
        """Copy-on-write guard for the position range ``[start, start+n)``
        of ``slot``: any page the write would touch that is still shared
        (refcount > 1) is replaced by a freshly allocated copy and the
        block-table entry swapped, so shared pages are only ever READ.
        By construction the admission path never maps a to-be-written block
        to a shared page, so this is an invariant net, not a hot path."""
        if n < 1 or self._allocator is None:
            return
        bs = self.kv_block_size
        lo = max(0, start // bs)
        # decode overshoot past the table scatters into page 0 — no COW
        hi = min((start + n - 1) // bs, self.max_blocks_per_slot - 1)
        for bidx in range(lo, hi + 1):
            with self._lock:
                old = int(self._block_tables[slot, bidx])
                if old == 0 or self._allocator.refcount(old) <= 1:
                    continue
                if self._allocator.free_blocks < 1 and self._prefix is not None:
                    evicted = self._prefix.evict(1, self._evictable)
                    if evicted:
                        self._allocator.free(evicted)
                new = self._allocator.alloc(1)[0]  # typed shed if truly none
            # the old page holds >= 2 refs (ours included) so it cannot be
            # reallocated while the device copy reads it
            self._cache = self._copy_page(self._cache, jnp.int32(old), jnp.int32(new))
            with self._lock:
                bl = self._slot_blocks[slot]
                bl[bl.index(old)] = new
                self._block_tables[slot, bidx] = new
                self._allocator.free([old])
                self._cow_count += 1

    def _prefill_tick(self) -> bool:
        """Advance the head prefilling request by one chunk. Returns True if
        any device work ran (the loop then skips its idle wait).

        With ``prefill_chunk_tokens > 0`` every chunk is the same fixed
        width, so a single compiled program serves all prompts and a decode
        step runs between chunks (Sarathi-style stall bounding). With 0 the
        whole prompt goes in one bucketed call."""
        with self._lock:
            while self._prefilling and self._prefilling[0].cancelled:
                req = self._prefilling.pop(0)
                self._release_blocks_locked(req.slot)
                self.num_shed += 1
                admission.record_shed("engine", "disconnect")
                self._record_done(
                    req, "disconnect", "stream consumer gone during prefill"
                )
                if not req.future.done():
                    req.future.set_exception(
                        RuntimeError("stream consumer disconnected during prefill")
                    )
                if req.stream_queue is not None:
                    req.stream_queue.put(_STREAM_END)
            if not self._prefilling:
                return False
            req = self._prefilling[0]
            gauges = self._pool_gauges_locked()
        self._publish_pool_gauges(*gauges)
        tp = len(req.prompt)
        start = req.prefill_pos
        chunk = self.prefill_chunk_tokens
        # one-shot width buckets the UNCACHED suffix, not the whole prompt:
        # a warm request's TTFT is proportional to what it actually computes
        width = min(chunk, self.S) if chunk > 0 else _bucket(tp - start, cap=self.S)
        n = min(width, tp - start)
        toks = np.zeros((1, width), np.int32)
        toks[0, :n] = req.prompt[start : start + n]
        stalled = bool(self._active.any())
        t0 = time.perf_counter()
        try:
            # invariant net: admission never maps a to-be-written block to a
            # shared page (the full-hit tail is COW'd eagerly), but writes
            # must still never land on refcount > 1 pages
            self._cow_shared_writes(req.slot, start, n)
            bt = jnp.asarray(self._block_tables[req.slot : req.slot + 1])
            logits, self._cache = self._prefill_chunk(
                self.params, self._cache, jnp.asarray(toks), bt,
                jnp.int32(start), jnp.int32(n),
            )
            jax.block_until_ready(logits)
        except BaseException as exc:  # noqa: BLE001
            with self._lock:
                self._prefilling.pop(0)
            self._fail_admit(req, exc)
            return True
        if stalled:
            # decode slots sat idle while this chunk ran; chunking bounds it
            metric_defs.LLM_DECODE_STALL.observe(time.perf_counter() - t0)
            self._note_stall()
        metric_defs.LLM_PREFILL_CHUNKS.inc()
        if req.trace is not None:
            req.trace.note_prefill_chunk()
        with self._lock:
            self._prefill_chunk_count += 1
        req.prefill_pos = start + n
        if req.prefill_pos < tp:
            return True
        with self._lock:
            self._prefilling.pop(0)
            self._prefill_count += 1
        try:
            self._finish_prefill(req, logits)
        except BaseException as exc:  # noqa: BLE001
            self._fail_admit(req, exc)
        return True

    def _maybe_finish(self, req: GenRequest, tok: int) -> bool:
        done = len(req.generated) >= req.max_tokens or (
            req.eos_id is not None and tok == req.eos_id
        )
        if done:
            evicted_n = 0
            with self._lock:
                self._active[req.slot] = False
                self._slots[req.slot] = None
                if self._allocator is not None:
                    evicted_n = self._retire_blocks_locked(req)
                    gauges = self._pool_gauges_locked()
            if self._allocator is not None:
                if evicted_n:
                    metric_defs.LLM_PREFIX_EVICTIONS.inc(evicted_n)
                self._publish_pool_gauges(*gauges)
            self._record_done(req, "finish")
            req.future.set_result(req.generated)
            if req.stream_queue is not None:
                req.stream_queue.put(_STREAM_END)
        return done

    def _step(self) -> None:
        toks = jnp.asarray(self._last_tok)
        pos = jnp.asarray(self._pos)
        if self.cache_kind == "paged":
            # copy-on-write net: a decode chunk writes positions
            # [pos, pos + K) — if any of those blocks still maps to a
            # shared page, give the slot its own copy before stepping
            for i in range(self.B):
                if self._active[i]:
                    self._cow_shared_writes(i, int(self._pos[i]), self.decode_chunk)
            # inactive rows decode through all-zero tables -> garbage page 0,
            # so freed pages are never written after release
            bt = jnp.asarray(self._block_tables * self._active[:, None].astype(np.int32))
            out, self._cache, self._key = self._decode_k_paged(
                self.params, self._cache, toks, pos,
                jnp.asarray(self._temps), self._key, bt,
            )
        else:
            out, self._cache, self._key = self._decode_k(
                self.params, self._cache, toks, pos,
                jnp.asarray(self._temps), self._key,
            )
        sampled = np.asarray(out)  # [B, K]
        for k in range(sampled.shape[1]):
            for i in range(self.B):
                # rt-lint: disable=lock-discipline -- engine-thread-owned:
                # every _slots mutation (admit/finish/evict/fail_inflight)
                # runs on this same engine loop thread; _lock exists for
                # cross-thread READERS (stats, abandon flags), not for us
                req = self._slots[i]
                if req is None:
                    continue  # free, or finished earlier in this chunk
                tok = int(sampled[i, k])
                req.generated.append(tok)
                self._note_next_token(req)
                req.emit(tok)
                self._pos[i] += 1
                self._last_tok[i] = tok
                self._maybe_finish(req, tok)

    def _reset_cache(self) -> None:
        """(Re)allocate the decode cache — also the recovery path after a
        failed donated step leaves the old buffers deleted."""
        if self.cache_kind == "paged":
            self._cache = init_paged_cache(self.cfg, self.kv_num_blocks, self.kv_block_size)
            return
        cache = init_cache(self.cfg, self.B, self.S)
        if self._kv_spec is not None:
            cache = {k: jax.device_put(v, self._kv_spec) for k, v in cache.items()}
        self._cache = cache

    def _fail_inflight(self, error: BaseException) -> None:
        """Fail every queued, prefilling, and in-slot request (loop-crash
        recovery): futures resolve with the error, stream iterators
        terminate, and every reserved KV page returns to the pool."""
        with self._lock:
            victims = self._queue.drain() + [r for r in self._slots if r is not None]
            victims += self._prefilling
            self._prefilling.clear()
            if self._held_req is not None:
                victims.append(self._held_req)
                self._held_req = None
            self._queued_tokens = 0
            self._slots = [None] * self.B
            self._active[:] = False
            if self._allocator is not None:
                for i in range(self.B):
                    self._release_blocks_locked(i)
                if self._prefix is not None:
                    # the device pool is about to be re-initialized; cached
                    # page CONTENTS die with it, so the index must too —
                    # drop every node and its reference unconditionally
                    stale = self._prefix.drain()
                    if stale:
                        self._allocator.free(stale)
        metric_defs.ADMISSION_QUEUE_DEPTH.set(0, self._depth_tags)
        if self._allocator is not None:
            self._publish_pool_gauges(0, 0, 0)
        for r in victims:
            self._record_done(r, "crash", str(error))
            if not r.future.done():
                r.future.set_exception(error)
            if r.stream_queue is not None:
                r.stream_queue.put(_STREAM_END)

    def _evict_cancelled(self) -> None:
        """Free decode slots whose streaming consumer went away: the slot
        (and its KV pages) returns to the batch NOW instead of decoding to an
        abandoned queue until stop/length
        (llm_slots_evicted_total{reason=disconnect})."""
        with self._lock:
            victims = [
                (i, r) for i, r in enumerate(self._slots)
                if r is not None and r.cancelled
            ]
            for i, _ in victims:
                self._slots[i] = None
                self._active[i] = False
                if self._allocator is not None:
                    self._release_blocks_locked(i)
            gauges = self._pool_gauges_locked()
        if victims and self._allocator is not None:
            self._publish_pool_gauges(*gauges)
        for _, r in victims:
            self.num_slots_evicted += 1
            metric_defs.LLM_SLOTS_EVICTED.inc(tags=_EVICT_DISCONNECT_TAGS)
            self._record_done(r, "disconnect", "decode slot evicted mid-stream")
            if not r.future.done():
                r.future.set_exception(
                    RuntimeError("stream consumer disconnected; decode slot evicted")
                )

    def _loop(self) -> None:
        while not self._stop:
            try:
                self._evict_cancelled()
                self._admit()
                progressed = False
                if self.cache_kind == "paged":
                    progressed = self._prefill_tick()
                if self._active.any():
                    self._step()
                elif not progressed:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
            except BaseException as exc:  # noqa: BLE001 — a dead loop hangs every caller
                # flight-record the crash BEFORE recovery clears the
                # evidence: admission state + the last finished requests
                from ray_tpu.observability import reqtrace

                reqtrace.flight_record(
                    "engine_crash",
                    f"LLMEngine loop crashed: {exc!r}",
                    severity="ERROR",
                    state=self.admission_snapshot(),
                    requests=list(self._finished_ring)[-8:],
                    engine=str(self._admission_token),
                )
                self._fail_inflight(RuntimeError(f"LLMEngine step failed: {exc!r}"))
                # a failed donated step leaves self._cache pointing at
                # deleted buffers; reallocate so the engine keeps serving
                self._reset_cache()


class LLMServer:
    """Serve deployment wrapper: each replica owns an engine.

    ``model_factory`` -> (cfg, params) or (cfg, params, tokenizer); called
    once per replica so weights live replica-local (HBM). With a tokenizer
    (anything exposing ``encode(str) -> ids`` / ``decode(ids) -> str``, e.g.
    a HuggingFace tokenizer), requests may pass ``text`` instead of
    ``prompt`` and responses carry decoded ``text``. Deploy with::

        app = serve.deployment(LLMServer).bind(model_factory, max_batch_size=8)
        handle = serve.run(app)
        handle.remote({"prompt": [1,2,3], "max_tokens": 16}).result()
        handle.remote({"text": "once upon", "max_tokens": 16}).result()
    """

    def __init__(
        self,
        model_factory: Callable[[], Any],
        *,
        max_batch_size: int = 8,
        max_seq_len: int = 512,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        quantize: bool = False,
        mesh: Optional[Any] = None,
        tp: str = "tp",
        decode_chunk: int = 1,
        max_queued_requests: int = 256,
        max_queued_prefill_tokens: int = 0,
        tenant_weights: Optional[Dict[str, float]] = None,
        cache_kind: Optional[str] = None,
        kv_block_size: Optional[int] = None,
        kv_num_blocks: Optional[int] = None,
        prefill_chunk_tokens: Optional[int] = None,
        prefix_cache: Optional[bool] = None,
        prefix_cache_max_blocks: Optional[int] = None,
        role: Optional[str] = None,
    ):
        made = model_factory()
        cfg, params = made[0], made[1]
        self.tokenizer = made[2] if len(made) > 2 else None
        self.role = role or ""
        self.engine = LLMEngine(
            cfg,
            params,
            max_batch_size=max_batch_size,
            max_seq_len=max_seq_len,
            top_k=top_k,
            top_p=top_p,
            quantize=quantize,
            mesh=mesh,
            tp=tp,
            decode_chunk=decode_chunk,
            max_queued_requests=max_queued_requests,
            max_queued_prefill_tokens=max_queued_prefill_tokens,
            tenant_weights=tenant_weights,
            cache_kind=cache_kind,
            kv_block_size=kv_block_size,
            kv_num_blocks=kv_num_blocks,
            prefill_chunk_tokens=prefill_chunk_tokens,
            prefix_cache=prefix_cache,
            prefix_cache_max_blocks=prefix_cache_max_blocks,
            role=role,
        )

    def _encode(self, request: Dict[str, Any]) -> List[int]:
        if "prompt" in request:
            return request["prompt"]
        if "text" in request:
            if self.tokenizer is None:
                raise ValueError("this deployment has no tokenizer; send 'prompt' token ids")
            return list(self.tokenizer.encode(request["text"]))
        raise ValueError("request needs 'prompt' (token ids) or 'text'")

    def __call__(self, request: Dict[str, Any]):
        prompt = self._encode(request)
        kw = dict(
            max_tokens=int(request.get("max_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
        )
        if request.get("stream"):
            # submit EAGERLY so validation errors surface as a normal error
            # response, not mid-stream corruption after a 200 was sent;
            # the returned generator of per-token events reaches the proxy
            # by reference (in-proc replicas) and renders as SSE
            stream = self.engine.submit_stream(prompt, **kw)

            def events():
                n = 0
                for tok in stream:
                    n += 1
                    yield {"token": tok}
                yield {"done": True, "num_generated": n}

            return events()
        t0 = time.perf_counter()
        out = self.engine.generate(prompt, **kw)
        resp = {
            "tokens": out,
            "num_generated": len(out),
            "latency_s": round(time.perf_counter() - t0, 4),
        }
        if self.tokenizer is not None:
            resp["text"] = self.tokenizer.decode(out)
        return resp

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    # -- disaggregated prefill/decode (called by the router's dispatcher) --
    def disagg_prefill(self, request: Dict[str, Any], mig_id: str) -> dict:
        """Prefill-pool half of a disaggregated request: chunked prefill +
        stage, returning the header-only migration ticket."""
        prompt = self._encode(request)
        return self.engine.prefill_export(
            prompt,
            mig_id=mig_id,
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
        ).result()

    def disagg_decode(self, request: Dict[str, Any], ticket: dict):
        """Decode-pool half: probe the local prefix cache, pull only the
        uncached-suffix blocks (device rung first, host fallback after),
        adopt into the continuous batch and run decode to completion.
        Migration failures return the typed-error envelope the dispatcher
        converts into KVMigrationError — the re-prefill ladder, not a
        crashed request."""
        from ray_tpu.serve import disagg

        prompt = list(ticket["prompt"])
        bs = self.engine.kv_block_size
        n_blocks = int(ticket["n_blocks"])
        matched = self.engine.peek_prefix_match(prompt)
        arrays: Dict[int, Any] = {}
        rung = "device"
        try:
            for bidx in range(matched // bs, n_blocks):
                arr, r = disagg.pull_block(ticket, bidx)
                if r != "device":
                    rung = r
                arrays[bidx] = arr
        except disagg.KVMigrationError as exc:
            return {"_kv_migration_error": True, "stage": exc.stage,
                    "message": str(exc)}
        kw = dict(
            max_tokens=int(request.get("max_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
        )
        if request.get("stream"):
            import queue as _queue

            q: "_queue.Queue" = _queue.Queue()
            req = self.engine.adopt_migration(
                ticket, arrays, _stream_queue=q, **kw
            )
            stream = _TokenStream(
                self.engine._stream_iter(req, q), req, self.engine
            )

            def events():
                n = 0
                for tok in stream:
                    n += 1
                    yield {"token": tok}
                yield {"done": True, "num_generated": n}

            return {"_stream": events(), "_migration_rung": rung}
        t0 = time.perf_counter()
        req = self.engine.adopt_migration(ticket, arrays, **kw)
        try:
            out = req.future.result()
        except disagg.KVMigrationError as exc:
            return {"_kv_migration_error": True, "stage": exc.stage,
                    "message": str(exc)}
        except RuntimeError as exc:
            cause = exc.__cause__
            if isinstance(cause, disagg.KVMigrationError):
                return {"_kv_migration_error": True, "stage": cause.stage,
                        "message": str(cause)}
            raise
        resp = {
            "tokens": out,
            "num_generated": len(out),
            "latency_s": round(time.perf_counter() - t0, 4),
            "_migration_rung": rung,
        }
        if self.tokenizer is not None:
            resp["text"] = self.tokenizer.decode(out)
        return resp

    def disagg_release(self, mig_id: str) -> bool:
        """Drop a staged export (dispatcher calls exactly once per
        migration, whatever the outcome)."""
        return self.engine.release_migration(mig_id)

    def kv_free_blocks(self) -> int:
        """Decode-pool routing signal for the role-aware router."""
        return self.engine.kv_free_blocks()

    def __del__(self):
        try:
            self.engine.shutdown()
        except Exception:
            pass


class OpenAICompatLLMServer(LLMServer):
    """OpenAI-compatible request/response adapter over :class:`LLMServer`.

    Accepts the body shapes of ``POST /v1/completions`` (``model`` +
    ``prompt``) and ``POST /v1/chat/completions`` (``model`` +
    ``messages``) and answers in the matching OpenAI response envelopes,
    including streaming chunk events over the proxy's SSE path.  Dispatch
    is by body shape — the HTTP proxy routes whole apps by path prefix, so
    one deployment serves both the native protocol and the OpenAI one.
    (Beyond reference parity: the reference delegates OpenAI-compatible
    LLM serving to vLLM.)

    Text prompts/messages need the model_factory to supply a tokenizer;
    token-id prompts work without one.  ``stop`` supports a single token id
    (honored in-engine as eos) or, with a tokenizer, a string trimmed from
    the non-streaming response.
    """

    def __call__(self, request: Any):
        if isinstance(request, dict) and ("messages" in request or "model" in request):
            return self._openai(request)
        return super().__call__(request)

    # ------------------------------------------------------------- openai
    def _openai(self, body: Dict[str, Any]):
        import uuid

        self._reject_unsupported(body)
        chat = "messages" in body
        prompt_ids = self._openai_prompt(body, chat)
        stop = body.get("stop")
        eos_id = None
        stop_text = None
        if isinstance(stop, int):
            eos_id = stop
        elif isinstance(stop, str):
            if self.tokenizer is not None:
                enc = self.tokenizer.encode(stop)
                if len(enc) == 1:
                    eos_id = enc[0]
                else:
                    stop_text = stop
            else:
                raise ValueError("string stop requires a tokenizer")
        elif isinstance(stop, list) and len(stop) == 1:
            return self._openai({**body, "stop": stop[0]})
        elif stop is not None:
            raise ValueError("stop: a single token id or string is supported")

        kw = dict(
            max_tokens=int(body.get("max_tokens", 16)),
            # OpenAI semantics: absent temperature means 1.0 (sampling) —
            # defaulting to greedy here would silently answer a different
            # distribution than every OpenAI SDK client expects
            temperature=float(body.get("temperature", 1.0)),
            eos_id=eos_id,
        )
        rid = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
        model = body.get("model", "ray_tpu")
        created = int(time.time())
        obj = "chat.completion" if chat else "text_completion"

        if body.get("stream"):
            if stop_text is not None:
                raise ValueError(
                    "streaming with a multi-token stop string is not "
                    "supported — use a stop that encodes to one token"
                )
            stream = self.engine.submit_stream(prompt_ids, **kw)

            def chunks():
                reason = "length"
                for tok in stream:
                    if eos_id is not None and tok == eos_id:
                        # OpenAI semantics: the stop sequence is excluded
                        # from the streamed output
                        reason = "stop"
                        continue  # engine ends the stream after eos
                    piece = (
                        self.tokenizer.decode([tok])
                        if self.tokenizer is not None
                        else None
                    )
                    delta = (
                        {"delta": {"content": piece}, "index": 0, "finish_reason": None}
                        if chat
                        else {"text": piece, "token_ids": [tok], "index": 0,
                              "finish_reason": None}
                    )
                    yield {"id": rid, "object": obj + ".chunk", "created": created,
                           "model": model, "choices": [delta]}
                final = (
                    {"delta": {}, "index": 0, "finish_reason": reason}
                    if chat
                    else {"text": "", "index": 0, "finish_reason": reason}
                )
                yield {"id": rid, "object": obj + ".chunk", "created": created,
                       "model": model, "choices": [final]}

            return chunks()

        out = self.engine.generate(prompt_ids, **kw)
        finish = "stop" if (eos_id is not None and out and out[-1] == eos_id) else "length"
        if finish == "stop":
            out = out[:-1]  # OpenAI semantics: stop sequence excluded
        text = self.tokenizer.decode(out) if self.tokenizer is not None else None
        if text is not None and stop_text and stop_text in text:
            # trim at TOKEN granularity so token_ids stay faithful to what
            # the model generated (re-encoding trimmed text could produce
            # ids the model never emitted): keep the longest generated
            # prefix whose decode does not yet contain the stop text, and
            # derive text from it so decode(token_ids) == text
            # contains-stop is monotone in the prefix length, so binary
            # search the cut (a linear scan would decode O(n) prefixes on
            # the serving hot path when the stop lands early)
            lo, hi = 0, len(out)  # invariant: decode(out[:lo]) lacks stop
            while lo < hi:
                mid = (lo + hi + 1) // 2
                if stop_text in self.tokenizer.decode(out[:mid]):
                    hi = mid - 1
                else:
                    lo = mid
            out = out[:lo]
            text = self.tokenizer.decode(out)
            finish = "stop"
        choice: Dict[str, Any] = {"index": 0, "finish_reason": finish, "token_ids": out}
        if chat:
            choice["message"] = {"role": "assistant", "content": text}
        else:
            choice["text"] = text
        return {
            "id": rid,
            "object": obj,
            "created": created,
            "model": model,
            "choices": [choice],
            "usage": {
                "prompt_tokens": len(prompt_ids),
                "completion_tokens": len(out),
                "total_tokens": len(prompt_ids) + len(out),
            },
        }

    def _reject_unsupported(self, body: Dict[str, Any]) -> None:
        """Unimplemented OpenAI sampling params fail loudly — silently
        ignoring them would return samples the client didn't ask for.
        Values matching OpenAI defaults (top_p=1, n=1, zero penalties)
        pass, since SDKs send those unprompted."""
        bad = []
        top_p = body.get("top_p")
        if top_p is not None and top_p < 1.0:
            # sampling config is per-ENGINE: a request may restate the
            # engine's own top_p, but asking for a different distribution
            # must not be silently overridden.  top_p=1.0 always passes —
            # SDKs send the OpenAI default unprompted.
            eng_p = self.engine.top_p
            if eng_p is None or abs(float(top_p) - float(eng_p)) > 1e-9:
                bad.append(
                    f"top_p={top_p} (engine is configured with "
                    f"top_p={eng_p}; per-request nucleus sampling is not "
                    "supported — configure it on the deployment)"
                )
        if body.get("n", 1) not in (None, 1):
            bad.append("n > 1")
        if body.get("best_of", 1) not in (None, 1):
            bad.append("best_of > 1")
        lp = body.get("logprobs")
        if lp is not None and lp is not False:  # NOT `in (None, False)`: 0 == False
            bad.append("logprobs")
        for k in ("presence_penalty", "frequency_penalty"):
            if body.get(k):
                bad.append(k)
        if body.get("echo"):
            bad.append("echo")
        if bad:
            raise ValueError(
                "unsupported OpenAI parameter(s): " + ", ".join(bad)
            )

    def _openai_prompt(self, body: Dict[str, Any], chat: bool) -> List[int]:
        if chat:
            messages = body["messages"]
            if self.tokenizer is None:
                raise ValueError("chat completions require a tokenizer")
            template = getattr(self.tokenizer, "apply_chat_template", None)
            if template is not None:
                ids = template(messages, add_generation_prompt=True)
                return list(ids)
            joined = "\n".join(f"{m['role']}: {m['content']}" for m in messages)
            return list(self.tokenizer.encode(joined + "\nassistant:"))
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompts require a tokenizer")
            return list(self.tokenizer.encode(prompt))
        if isinstance(prompt, list) and all(isinstance(t, int) for t in prompt):
            return prompt
        raise ValueError("prompt must be a string or a list of token ids")
