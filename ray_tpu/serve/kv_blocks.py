"""Free-list allocator for the paged KV cache's HBM block pool.

The serving engine's paged cache (``models/generation.init_paged_cache``)
is one shared pool of fixed-size pages per layer; sequences own disjoint
sets of pages named by their block tables. This module is the host-side
bookkeeping: which pages are free, which are held, and a typed
``OverloadedError`` (the PR-9 admission contract, with ``retry_after_s``)
when a request asks for more pages than are currently free.

Page 0 is never handed out: it is the **garbage page**. Inactive decode
rows and bucket-padded prefill tails scatter their K/V through all-zero
block-table entries, and pointing those at a sacrificial page is what lets
one static-shape decode program serve every allocation pattern without
masking writes per row. Attention masks page 0 out by length, so its
contents are never read. It is also never SHARED: sharing it would give it
a refcount, and a refcount on the sentinel would let a release path return
it to the free list.

Pages are **reference counted** so the prefix cache can share one physical
page into many block tables (vLLM-style): ``alloc`` hands pages out at
refcount 1, ``share`` takes another reference on already-held pages, and
``free`` drops one reference — the page re-enters the free list only when
the last holder lets go. A holder is either a live request (one reference
per block-table entry) or the prefix cache (one reference per cached
node), so every existing release path stays a plain ``free`` of the slot's
pages.

Not thread-safe on its own: the engine serializes every alloc/share/free
under its admission lock, same as the WeightedFairQueue.
"""

from __future__ import annotations

from typing import Dict, List

from ray_tpu.runtime import admission


class BlockAllocator:
    """LIFO free list over pages ``1..num_blocks-1`` (page 0 reserved).

    Alloc/free are O(n) in the request's own block count and allocation
    order cannot fragment: pages are interchangeable (the block table
    provides the indirection), so ANY ``n <= free_blocks`` pages satisfy a
    request — there is no adjacency requirement to fragment against.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"pool needs >= 2 blocks (1 usable + the garbage page), got {num_blocks}"
            )
        self.num_blocks = int(num_blocks)
        #: pages a single request may ever hold (pool minus the garbage page)
        self.capacity = self.num_blocks - 1
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        # page -> reference count; a page is either on the free list or in
        # here with count >= 1, never both
        self._refs: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity - len(self._free)

    @property
    def shared_blocks(self) -> int:
        """Pages currently held by more than one reference (the
        ``llm_kv_blocks_shared`` gauge)."""
        return sum(1 for c in self._refs.values() if c > 1)

    def refcount(self, block: int) -> int:
        """References on ``block`` (0 = free or the garbage page). The
        copy-on-write rule reads this: a write may only land on a page with
        refcount 1."""
        return self._refs.get(block, 0)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` pages off the free list at refcount 1; raises the
        typed admission shed (``OverloadedError`` with ``retry_after_s``)
        when fewer than ``n`` are free — the caller leaves the request
        queued and retries as release paths return pages."""
        if n < 1:
            raise ValueError(f"alloc wants >= 1 block, got {n}")
        if n > len(self._free):
            raise admission.shed(
                "engine", "kv_blocks",
                message=(
                    f"KV block pool exhausted: {n} blocks wanted, "
                    f"{len(self._free)} of {self.capacity} free"
                ),
            )
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._refs[b] = 1
        return blocks

    def share(self, blocks: List[int]) -> None:
        """Take one more reference on each page (prefix-cache hit: the same
        physical page enters another block table). Only held pages can be
        shared — sharing a free page or the garbage page 0 is corruption
        and raises, same contract as double-free."""
        for b in blocks:
            if b not in self._refs:
                raise ValueError(f"sharing block {b} that is not held")
        for b in blocks:
            self._refs[b] += 1

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per page; a page returns to the pool only at
        refcount 0. Double-frees and foreign pages raise — a leak check
        must see corruption, not absorb it."""
        for b in blocks:
            if b not in self._refs:
                raise ValueError(f"freeing block {b} that is not held")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)
