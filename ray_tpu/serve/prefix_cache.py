"""Radix prefix cache over the paged KV block pool.

Production LLM traffic is prefix-heavy — shared system prompts, few-shot
templates, multi-turn chats — and the engine's block-table indirection
(`serve/kv_blocks.py`) is exactly the mechanism vLLM's PagedAttention and
SGLang's RadixAttention use to make shared prefixes free: if a FULL block
of tokens was already prefetched into some page, a new request can name
that same physical page in its own block table and skip the prefill
compute for it entirely.

This module is the host-side index mapping token prefixes to pages. It is
a hash chain (a radix tree whose edges are whole blocks): node ``i`` of a
chain is keyed by the running blake2b digest

    key_i = blake2b(key_{i-1} || tokens[i*bs : (i+1)*bs])

so lookup never compares token lists, only digests, and two prompts share
chain nodes exactly as far as they share block-aligned token prefixes.
Python's ``hash()`` is per-process salted and never used here — keys (and
therefore eviction order) are deterministic across processes and runs.

Ownership: the cache holds ONE allocator reference per node (taken over
from the finishing request at ``insert``). A cache hit ``share()``s the
matched pages into the requesting block table, so a page's refcount is
``1 (cache) + number of live requests naming it``. Eviction is LRU over
**unreferenced leaves only** — a leaf whose page has refcount 1 — with a
deterministic ``(last_used, seq)`` tie-break (``seq`` is insertion order),
so the same workload always evicts the same pages.

The cache never touches device memory and never calls the allocator: the
engine owns the allocator lock and frees/shares pages around these calls.
Not thread-safe on its own; the engine serializes access under its
admission lock.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

# digest of the chain root (depth -1); any constant works, but make it
# content-distinct from real node keys
_ROOT = hashlib.blake2b(b"ray_tpu.prefix_cache.root", digest_size=16).digest()


def chain_key(parent: bytes, tokens: Sequence[int]) -> bytes:
    """Running digest of one block's tokens chained onto ``parent``.
    Deterministic across processes (no Python ``hash``); token ids are
    encoded as fixed-width little-endian int64 so there is no ambiguity
    between e.g. [1, 23] and [12, 3]."""
    h = hashlib.blake2b(parent, digest_size=16)
    for t in tokens:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


@dataclass
class _Node:
    key: bytes
    parent: Optional[bytes]  # None for depth-0 nodes
    page: int
    seq: int  # insertion order — the deterministic LRU tie-break
    last_used: int  # monotonic touch counter (bumped on every match walk)
    children: int = 0  # live child count; leaf iff 0


class PrefixCache:
    """Longest-prefix index of FULL KV blocks: token chunks -> page ids.

    ``max_blocks`` bounds how many pages the cache may pin (0 = bounded
    only by the pool itself); at the bound, ``insert`` evicts LRU leaves to
    make room and stops adopting when nothing is evictable.
    """

    def __init__(self, block_size: int, max_blocks: int = 0):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        self.max_blocks = max(0, int(max_blocks))
        self._nodes: Dict[bytes, _Node] = {}
        self._tick = 0  # LRU clock: one bump per touch/insert
        self._seq = 0  # insertion counter (never reused)
        self.evictions = 0  # cumulative, for the evictions counter metric

    def __len__(self) -> int:
        return len(self._nodes)

    def keys(self) -> Set[bytes]:
        """Snapshot of live node keys (eviction-determinism tests compare
        these across identical workloads)."""
        return set(self._nodes)

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.last_used = self._tick

    # -- lookup --------------------------------------------------------------
    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens`` at full-block granularity.

        Returns ``(pages, matched_token_count)`` — ``pages[i]`` holds the
        KV of tokens ``[i*bs, (i+1)*bs)``. Every node on the path is
        touched (it is the LRU signal), including on walks whose request is
        later held; the caller ``share()``s the pages only when it actually
        admits."""
        bs = self.block_size
        pages: List[int] = []
        parent = _ROOT
        for i in range(len(tokens) // bs):
            key = chain_key(parent, tokens[i * bs : (i + 1) * bs])
            node = self._nodes.get(key)
            if node is None:
                break
            self._touch(node)
            pages.append(node.page)
            parent = key
        return pages, len(pages) * bs

    # -- insertion -----------------------------------------------------------
    def insert(
        self,
        tokens: Sequence[int],
        pages: Sequence[int],
        evictable: Callable[[int], bool],
    ) -> Tuple[Set[int], List[int]]:
        """Adopt the full blocks of ``tokens`` (``pages[i]`` is the caller's
        page for block ``i``) into the cache.

        Returns ``(adopted, evicted)``: ``adopted`` pages had their caller
        reference TRANSFERRED to the cache (the caller must not free them);
        ``evicted`` pages were dropped to stay under ``max_blocks`` and the
        caller must free the cache's reference on each. Blocks already
        cached adopt nothing — the caller keeps (and frees) its own copy.
        ``evictable(page)`` says whether only the cache still references a
        page (allocator refcount 1)."""
        bs = self.block_size
        adopted: Set[int] = set()
        evicted: List[int] = []
        parent = _ROOT
        parent_node: Optional[_Node] = None
        protect: Set[bytes] = set()  # the chain being built: never evict it
        for i in range(min(len(tokens) // bs, len(pages))):
            key = chain_key(parent, tokens[i * bs : (i + 1) * bs])
            node = self._nodes.get(key)
            if node is None:
                if self.max_blocks and len(self._nodes) >= self.max_blocks:
                    evicted += self.evict(
                        len(self._nodes) - self.max_blocks + 1,
                        evictable,
                        protect=protect,
                    )
                    if len(self._nodes) >= self.max_blocks:
                        break  # nothing evictable: stop adopting, keep what we have
                self._seq += 1
                self._tick += 1
                node = _Node(
                    key=key,
                    parent=None if parent is _ROOT else parent,
                    page=int(pages[i]),
                    seq=self._seq,
                    last_used=self._tick,
                )
                self._nodes[key] = node
                if parent_node is not None:
                    parent_node.children += 1
                adopted.add(int(pages[i]))
            else:
                self._touch(node)
            protect.add(key)
            parent = key
            parent_node = node
        return adopted, evicted

    # -- eviction ------------------------------------------------------------
    def evict(
        self,
        want: int,
        evictable: Callable[[int], bool],
        protect: Optional[Set[bytes]] = None,
    ) -> List[int]:
        """LRU sweep: drop up to ``want`` unreferenced leaves and return
        their pages (the caller frees the cache's reference on each).

        Deterministic: victims are chosen by ascending ``(last_used, seq)``
        — same workload, same eviction order. Evicting a leaf can expose
        its parent as the next leaf, so the sweep cascades up cold chains.
        Interior nodes and pages still shared into live requests are never
        taken."""
        freed: List[int] = []
        while len(freed) < want:
            victim: Optional[_Node] = None
            for nd in self._nodes.values():
                if nd.children:
                    continue
                if protect is not None and nd.key in protect:
                    continue
                if not evictable(nd.page):
                    continue
                if victim is None or (nd.last_used, nd.seq) < (victim.last_used, victim.seq):
                    victim = nd
            if victim is None:
                break
            del self._nodes[victim.key]
            if victim.parent is not None:
                parent = self._nodes.get(victim.parent)
                if parent is not None:
                    parent.children -= 1
            freed.append(victim.page)
            self.evictions += 1
        return freed

    def drain(self) -> List[int]:
        """Drop EVERY node regardless of sharing and return all pages the
        cache held a reference on. Used when the device-side pool is gone
        (loop-crash cache reset): the page contents no longer exist, so the
        index must not survive them."""
        pages = [nd.page for nd in self._nodes.values()]
        self._nodes.clear()
        return pages
