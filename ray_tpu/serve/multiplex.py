"""Model multiplexing: many models per replica, LRU-resident.

Parity: ``python/ray/serve/multiplex.py`` — ``@serve.multiplexed`` wraps an
async/sync model loader; per-model instances are cached per replica with an
LRU cap (``max_num_models_per_replica``).  On TPU this is the many-LoRA /
many-finetune pattern: models share the replica's device slice and swap in
HBM.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

_current_model_id = threading.local()


def get_multiplexed_model_id() -> str:
    """Inside a replica call: the model id of the current request."""
    return getattr(_current_model_id, "value", "")


def set_multiplexed_model_id(model_id: str) -> None:
    _current_model_id.value = model_id


def multiplexed(_fn: Optional[Callable] = None, *, max_num_models_per_replica: int = 3):
    def wrap(loader):
        cache_holder: dict = {}
        lock = threading.Lock()

        @functools.wraps(loader)
        def get_model(self_or_id, model_id: Optional[str] = None):
            if model_id is None:
                instance, model_id = None, self_or_id
            else:
                instance = self_or_id
            key = id(instance)
            with lock:
                cache = cache_holder.setdefault(key, OrderedDict())
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
            model = loader(instance, model_id) if instance is not None else loader(model_id)
            with lock:
                cache = cache_holder[key]
                cache[model_id] = model
                cache.move_to_end(model_id)
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
            return model

        return get_model

    if _fn is not None:
        return wrap(_fn)
    return wrap
