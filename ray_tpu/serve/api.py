"""serve public API: run/start/shutdown/status/get_deployment_handle.

Parity: ``python/ray/serve/api.py`` — ``serve.run(app)`` deploys a bound
application graph and returns the ingress handle; composition materializes
child Applications as DeploymentHandles passed to parent constructors
(deployment-graph semantics, SURVEY §3.6).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.controller import ServeControllerActor
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.proxy import HTTPProxy
from ray_tpu.serve.router import DeploymentHandle

_state_lock = threading.RLock()
_controller = None
_proxy: Optional[HTTPProxy] = None
_grpc_proxy = None
_apps: Dict[str, DeploymentHandle] = {}  # app name -> ingress handle
_topology: Dict[str, dict] = {}  # app name -> deployment DAG (dashboard view)


@dataclass
class HTTPOptions:
    """HTTP ingress options (parity: serve.config.HTTPOptions — the
    subset the proxy honors; pass to ``serve.start(http_options=...)``)."""

    host: str = "127.0.0.1"
    port: int = 0
    request_timeout_s: float = 30.0


def start(
    *,
    http_host: str = "127.0.0.1",
    http_port: int = 0,
    request_timeout_s: float = 30.0,
    grpc_port: Optional[int] = None,
    grpc_allow_pickle: bool = False,
    http_options: Optional[HTTPOptions] = None,
):
    """Start the Serve instance (controller + HTTP proxy; pass ``grpc_port``
    — 0 for an ephemeral port — to also open the gRPC ingress, parity with
    the reference's gRPCOptions). ``grpc_allow_pickle`` enables the pickle
    payload codec — trusted networks only (pickle executes client bytes)."""
    if http_options is not None:
        http_host = http_options.host
        http_port = http_options.port
        request_timeout_s = http_options.request_timeout_s
    global _controller, _proxy, _grpc_proxy
    with _state_lock:
        if _controller is None:
            _controller = ServeControllerActor.options(execution="inproc", max_concurrency=64).remote()
            ray_tpu.get(_controller.ping.remote())
        if _proxy is None:
            _proxy = HTTPProxy(http_host, http_port, request_timeout_s)
        if _grpc_proxy is None and grpc_port is not None:
            from ray_tpu.serve.grpc_proxy import GRPCProxy

            _grpc_proxy = GRPCProxy(
                http_host, grpc_port, request_timeout_s, allow_pickle=grpc_allow_pickle
            )
            for app_name, handle in _apps.items():  # apps deployed pre-start
                _grpc_proxy.add_app(app_name, handle)
    return _controller


def _require_started():
    if _controller is None:
        start()
    return _controller


def run(app: Application, *, name: str = "default", route_prefix: Optional[str] = "/") -> DeploymentHandle:
    """Deploy an application graph; returns the ingress handle."""
    controller = _require_started()
    apps = app.walk()  # dependencies first
    # the DAG shape for the dashboard's topology view (reference: the
    # serve dashboard's application graph) — registered under the state
    # lock AFTER every deploy succeeds, beside _apps, so status() never
    # shows an app that failed to deploy or raced a shutdown
    topology = {
        "ingress": app.deployment.name,
        "route_prefix": route_prefix,
        "deployments": [
            {
                "name": sub.deployment.name,
                "num_replicas": sub.deployment.num_replicas,
                "depends_on": sorted(
                    {
                        a.deployment.name
                        for a in list(sub.init_args) + list(sub.init_kwargs.values())
                        if isinstance(a, Application)
                    }
                ),
            }
            for sub in apps
        ],
    }
    handles: Dict[int, DeploymentHandle] = {}
    for sub in apps:
        init_args = tuple(handles[id(a)] if isinstance(a, Application) else a for a in sub.init_args)
        init_kwargs = {
            k: (handles[id(v)] if isinstance(v, Application) else v) for k, v in sub.init_kwargs.items()
        }
        ray_tpu.get(controller.deploy.remote(sub.deployment, init_args, init_kwargs))
        handles[id(sub)] = DeploymentHandle(sub.deployment.name, controller)
    ingress = handles[id(app)]
    if route_prefix is not None:
        ray_tpu.get(controller.set_ingress.remote(route_prefix, app.deployment.name))
    # registration into the proxies/app map under the state lock: a
    # concurrent shutdown()/start() must not see a half-registered app or
    # register into a proxy being torn down
    with _state_lock:
        if route_prefix is not None and _proxy is not None:
            _proxy.add_route(route_prefix, ingress)
        _apps[name] = ingress
        _topology[name] = topology
        if _grpc_proxy is not None:
            _grpc_proxy.add_app(name, ingress)
    return ingress


def run_config(config) -> Dict[str, Any]:
    """Deploy from a declarative config: a dict, or a path to a YAML file
    (parity: ``serve deploy`` / ``serve run`` config path, serve/schema.py)."""
    from ray_tpu.serve import schema

    if isinstance(config, str):
        config = schema.load_config_file(config)
    return schema.deploy_config(config)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    controller = _require_started()
    return DeploymentHandle(deployment_name, controller)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    """Handle to a running application's ingress deployment (parity:
    serve.get_app_handle)."""
    with _state_lock:
        handle = _apps.get(name)
    if handle is None:
        raise KeyError(
            f"no running Serve application named {name!r}; deployed apps: "
            f"{sorted(_apps)}"
        )
    return handle


def _run(app, *, name: str = "default", route_prefix: Optional[str] = "/", **_ignored) -> DeploymentHandle:
    """Internal non-blocking deploy variant (reference serve._run — same
    behavior here because run() already returns without blocking)."""
    return run(app, name=name, route_prefix=route_prefix)


def ingress(app):
    """FastAPI ingress decorator (parity: serve.ingress).  The fastapi
    package is not installed in this environment; plain deployments with
    __call__ handlers and the HTTP proxy's route dispatch cover the
    native ingress path."""
    raise ImportError(
        "serve.ingress requires the fastapi package, which is not installed "
        "in this environment; define a deployment class with a __call__ "
        "(request) handler and serve.run(app, route_prefix=...) instead"
    )


def status() -> Dict[str, Any]:
    controller = _require_started()
    return {
        "deployments": ray_tpu.get(controller.list_deployments.remote()),
        "proxy_url": _proxy.url if _proxy else None,
        "grpc_address": _grpc_proxy.address if _grpc_proxy else None,
        "applications": dict(_topology),
    }


def delete(name: str) -> None:
    controller = _require_started()
    ray_tpu.get(controller.delete_deployment.remote(name))
    # drop app registrations / proxy routes whose ingress was this
    # deployment — a stale handle would surface as ActorDiedError next call.
    # Under the state lock so a concurrent shutdown()/start() can't race the
    # proxy map mutations.
    with _state_lock:
        for app, handle in list(_apps.items()):
            if getattr(handle, "deployment_name", None) == name:
                del _apps[app]
                _topology.pop(app, None)
        # deleting a non-ingress member invalidates its app's DAG too
        for app, topo in list(_topology.items()):
            if any(d["name"] == name for d in topo.get("deployments", ())):
                _topology.pop(app, None)
        if _grpc_proxy is not None:
            for app, handle in list(_grpc_proxy.apps.items()):
                if getattr(handle, "deployment_name", None) == name:
                    _grpc_proxy.remove_app(app)
        if _proxy is not None:
            for prefix, handle in list(_proxy.routes.items()):
                if getattr(handle, "deployment_name", None) == name:
                    _proxy.remove_route(prefix)


def proxy_url() -> Optional[str]:
    return _proxy.url if _proxy else None


def grpc_address() -> Optional[str]:
    """host:port of the gRPC ingress, or None when not started."""
    return _grpc_proxy.address if _grpc_proxy else None


def shutdown() -> None:
    global _controller, _proxy, _grpc_proxy
    with _state_lock:
        _apps.clear()
        _topology.clear()
        if _grpc_proxy is not None:
            _grpc_proxy.shutdown()
            _grpc_proxy = None
        if _proxy is not None:
            _proxy.shutdown()
            _proxy = None
        if _controller is not None:
            try:
                ray_tpu.get(_controller.shutdown.remote())
                ray_tpu.kill(_controller)
            except Exception:
                pass
            _controller = None
        from ray_tpu.serve.router import clear_router_cache

        clear_router_cache()
