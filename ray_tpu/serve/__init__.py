"""ray_tpu.serve: online model serving.

TPU-native rebuild of the reference's Ray Serve (``python/ray/serve/``,
SURVEY §2.4/§3.6): a controller actor reconciles deployment replicas with
queue-depth autoscaling; handles route with power-of-two-choices; an HTTP
proxy fronts apps; ``@serve.batch`` shapes concurrent requests into MXU
batches; ``@serve.multiplexed`` LRU-caches many models per replica.
"""

from ray_tpu.serve.api import (
    HTTPOptions,
    _run,
    delete,
    get_app_handle,
    get_deployment_handle,
    ingress,
    grpc_address,
    proxy_url,
    run,
    run_config,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.deployment import Application, AutoscalingConfig, Deployment, deployment
from ray_tpu.serve.llm import LLMEngine, LLMServer, OpenAICompatLLMServer
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.replica import ReplicaContext, get_replica_context
from ray_tpu.serve.router import DeploymentHandle, DeploymentResponse

__all__ = [
    "Application",
    "LLMEngine",
    "LLMServer",
    "OpenAICompatLLMServer",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "HTTPOptions",
    "ReplicaContext",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_replica_context",
    "ingress",
    "get_multiplexed_model_id",
    "grpc_address",
    "multiplexed",
    "proxy_url",
    "run",
    "run_config",
    "shutdown",
    "start",
    "status",
]
