"""Router + DeploymentHandle: request routing with power-of-two-choices.

Parity: ``python/ray/serve/_private/router.py:312`` and
``replica_scheduler/pow_2_scheduler.py:49`` — the handle's router samples
two replicas and sends to the one with fewer in-flight requests (tracked
locally, optimistically), giving near-least-loaded balancing without a
global queue view.  ``DeploymentResponse`` is the future-like result
(parity: handle.py DeploymentResponse) and can be passed straight into
another handle call (composition without materializing).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.observability import metric_defs


def _is_system_failure(exc: BaseException) -> bool:
    """System-level failures the router may fail over; application
    exceptions propagate untouched (parity: the reference router only
    retries system errors)."""
    from ray_tpu.exceptions import (
        ObjectLostError,
        RayActorError,
        WorkerCrashedError,
    )

    return isinstance(exc, (RayActorError, WorkerCrashedError, ObjectLostError))


class DeploymentResponse:
    """Future-like result. The replica's in-flight count is settled by a
    completion callback the Router attached to the underlying ref, so a
    `result(timeout=...)` that times out (request still occupying the
    replica) or an abandoned response cannot skew pow-2 balancing.

    Replica-death failover: a request that raced a dying replica (the
    window between the kill and the controller's health-check replacement)
    reports the dead replica to the router (local prune — the controller's
    snapshot may still list it for ~a health-check period), waits for
    usable membership within the caller's deadline, and re-routes.  The
    retry replays the ORIGINAL request (nested DeploymentResponses
    included, so a lost upstream result can itself fail over)."""

    def __init__(self, ref, router=None, request=None, replica=None):
        self._ref = ref
        self._router = router
        self._request = request  # (method, args, kwargs) PRE-resolution
        self._replica = replica  # the actor handle this attempt targets

    def result(self, timeout: Optional[float] = None, *, timeout_s: Optional[float] = None) -> Any:
        # timeout_s: the reference's spelling (serve.handle.DeploymentResponse)
        import time as _time

        budget = timeout_s if timeout_s is not None else timeout
        deadline = None if budget is None else _time.monotonic() + budget
        while True:
            try:
                remaining = None if deadline is None else max(0.01, deadline - _time.monotonic())
                value = ray_tpu.get(self._ref, timeout=remaining)
                # retries are pointless after success: drop the replay
                # payload so the response doesn't pin args/router forever
                self._router = self._request = self._replica = None
                return value
            except Exception as exc:  # noqa: BLE001 — filtered below
                if (
                    self._router is None
                    or self._request is None
                    or not _is_system_failure(exc)
                    or (deadline is not None and _time.monotonic() >= deadline)
                ):
                    raise
                if self._replica is not None:
                    self._router.report_dead(self._replica)
                    self._replica = None
                method, args, kwargs = self._request
                retry = self._router.route_within(
                    method, args, kwargs,
                    deadline=deadline if deadline is not None else _time.monotonic() + 30.0,
                )
                if retry is None:
                    raise  # no usable membership before the deadline
                self._ref, self._replica = retry._ref, retry._replica

    def _to_object_ref(self):
        return self._ref


class Router:
    def __init__(self, deployment_name: str, controller_handle):
        self.deployment_name = deployment_name
        self.controller = controller_handle
        self._replicas: List[Any] = []
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._version = -1
        self._rng = random.Random()
        self._reqs_since_push = 0
        self._watching = False
        self._metric_tags = {"deployment": deployment_name}

    # ------------------------------------------------------------ updates
    def _apply_snapshot(self, version: int, replicas: List[Any]) -> None:
        with self._lock:
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._inflight = {i: self._inflight.get(i, 0) for i in range(len(replicas))}

    def _refresh(self, force: bool = False) -> None:
        # Membership updates arrive via a long-poll watcher (parity:
        # LongPollHost, serve/_private/long_poll.py); the synchronous pull
        # only runs before the first snapshot lands.
        if not self._watching:
            with self._lock:
                if self._watching:
                    return
                self._watching = True
            threading.Thread(
                target=self._watch_loop, daemon=True, name=f"serve-watch-{self.deployment_name}"
            ).start()
        if force or not self._replicas:
            version, replicas = ray_tpu.get(self.controller.get_replicas.remote(self.deployment_name))
            self._apply_snapshot(version, replicas)

    def _watch_loop(self) -> None:
        import time

        failures = 0
        while failures < 3:
            try:
                version, replicas = ray_tpu.get(
                    self.controller.poll_replicas.remote(self.deployment_name, self._version, 5.0),
                    timeout=30,
                )
                failures = 0
                self._apply_snapshot(version, replicas)
            except Exception:
                failures += 1
                time.sleep(0.5)
        # controller unreachable: stand down; the next route() restarts us
        with self._lock:
            self._watching = False

    # ------------------------------------------------------------ routing
    def report_dead(self, replica) -> None:
        """A caller observed this replica fail: prune it locally NOW — the
        controller's snapshot keeps listing it for up to a health-check
        period, and re-routing onto it just burns the retry."""
        with self._lock:
            if replica in self._replicas:
                self._replicas = [r for r in self._replicas if r is not replica]
                self._inflight = {i: 0 for i in range(len(self._replicas))}

    def route_within(self, method: str, args: tuple, kwargs: dict, *, deadline: float):
        """route(), but wait for usable membership (a live replica) up to
        ``deadline`` instead of failing fast; None if none appeared."""
        import time as _time

        while True:
            try:
                return self.route(method, args, kwargs)
            except RuntimeError:
                if _time.monotonic() >= deadline:
                    return None
                _time.sleep(0.1)
                self._refresh(force=True)

    def route(self, method: str, args: tuple, kwargs: dict) -> DeploymentResponse:
        t_start = time.perf_counter()
        if not self._replicas:
            self._refresh()
        if not self._replicas:
            raise RuntimeError(f"deployment {self.deployment_name!r} has no replicas")
        original_request = (method, args, kwargs)  # PRE-resolution, for replay
        with self._lock:
            n = len(self._replicas)
            if n == 1:
                idx = 0
            else:
                # power of two choices over locally-tracked in-flight counts
                a, b = self._rng.sample(range(n), 2)
                idx = a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) else b
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            total_inflight = sum(self._inflight.values())
            replica = self._replicas[idx]
            self._reqs_since_push += 1
            push = self._reqs_since_push >= 10
            if push:
                self._reqs_since_push = 0
        metric_defs.SERVE_ROUTER_REQUESTS.inc(tags=self._metric_tags)
        metric_defs.SERVE_ROUTER_INFLIGHT.set(total_inflight, self._metric_tags)
        metric_defs.SERVE_ROUTER_QUEUE_WAIT.observe(
            time.perf_counter() - t_start, tags=self._metric_tags
        )
        # Resolve nested DeploymentResponses: pass their refs so the fabric
        # chains the calls without blocking here (model composition).
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse) else a for a in args)
        kwargs = {k: (v._to_object_ref() if isinstance(v, DeploymentResponse) else v) for k, v in kwargs.items()}
        ref = replica.handle_request.remote(method, args, kwargs)
        # Ready-hook, not ref.future(): a future would pull every response
        # onto the router's node; the directory callback fires when the
        # result is committed anywhere, without materializing it here.
        from ray_tpu.api import get_cluster

        get_cluster().directory.wait_for(
            ref.id(), lambda _node, i=idx: self._request_finished(i)
        )
        if push:
            self._push_metrics()
        return DeploymentResponse(ref, router=self, request=original_request, replica=replica)

    def _push_metrics(self) -> None:
        try:
            self.controller.record_request_metrics.remote(
                self.deployment_name, dict(self._inflight)
            )
        except Exception:
            pass

    def _request_finished(self, idx: int) -> None:
        with self._lock:
            if idx in self._inflight and self._inflight[idx] > 0:
                self._inflight[idx] -= 1
            total_inflight = sum(self._inflight.values())
            drained = not total_inflight
        metric_defs.SERVE_ROUTER_INFLIGHT.set(total_inflight, self._metric_tags)
        if drained:
            # without this push the controller's last snapshot would show
            # ongoing requests forever and it would never scale down
            self._push_metrics()

    def stale(self) -> bool:
        return True


# One Router (and thus one long-poll watcher thread) per deployment per
# controller — handles are created freely (serve.run makes one per
# sub-deployment per call) and must not each spawn a watcher.
_router_cache: Dict[tuple, "Router"] = {}
_router_cache_lock = threading.Lock()


def _shared_router(deployment_name: str, controller_handle) -> "Router":
    key = (id(controller_handle), deployment_name)
    with _router_cache_lock:
        router = _router_cache.get(key)
        if router is None:
            router = _router_cache[key] = Router(deployment_name, controller_handle)
        return router


def clear_router_cache() -> None:
    """Called on serve.shutdown so stale watchers drain and a new serve
    instance gets fresh routers."""
    with _router_cache_lock:
        _router_cache.clear()


class DeploymentHandle:
    """What users (and the proxy) call (parity: serve DeploymentHandle)."""

    def __init__(self, deployment_name: str, controller_handle):
        self.deployment_name = deployment_name
        self._router = _shared_router(deployment_name, controller_handle)
        self._method = "__call__"

    def options(self, *, method_name: Optional[str] = None) -> "DeploymentHandle":
        h = DeploymentHandle.__new__(DeploymentHandle)
        h.deployment_name = self.deployment_name
        h._router = self._router
        h._method = method_name or self._method
        return h

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._router._refresh()
        return self._router.route(self._method, args, kwargs)

    def __getattr__(self, name: str) -> "_MethodCaller":
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._handle._router._refresh()
        return self._handle._router.route(self._method, args, kwargs)
