"""Router + DeploymentHandle: request routing with power-of-two-choices.

Parity: ``python/ray/serve/_private/router.py:312`` and
``replica_scheduler/pow_2_scheduler.py:49`` — the handle's router samples
two replicas and sends to the one with fewer in-flight requests (tracked
locally, optimistically), giving near-least-loaded balancing without a
global queue view.  ``DeploymentResponse`` is the future-like result
(parity: handle.py DeploymentResponse) and can be passed straight into
another handle call (composition without materializing).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.observability import metric_defs



def _is_system_failure(exc: BaseException) -> bool:
    """System-level failures the router may fail over; application
    exceptions propagate untouched (parity: the reference router only
    retries system errors)."""
    from ray_tpu.exceptions import (
        ObjectLostError,
        RayActorError,
        WorkerCrashedError,
    )

    return isinstance(exc, (RayActorError, WorkerCrashedError, ObjectLostError))


class DeploymentResponse:
    """Future-like result. The replica's in-flight count is settled by a
    completion callback the Router attached to the underlying ref, so a
    `result(timeout=...)` that times out (request still occupying the
    replica) or an abandoned response cannot skew pow-2 balancing.

    Replica-death failover: a request that raced a dying replica (the
    window between the kill and the controller's health-check replacement)
    reports the dead replica to the router (local prune — the controller's
    snapshot may still list it for ~a health-check period), waits for
    usable membership within the caller's deadline, and re-routes.  The
    retry replays the ORIGINAL request (nested DeploymentResponses
    included, so a lost upstream result can itself fail over) — but ONLY
    for deployments declared ``idempotent=True``: the dead replica may
    have executed its side effects before dying, so replaying a
    side-effecting deployment could execute it twice.  Non-idempotent
    deployments (the default) surface the typed actor error instead."""

    def __init__(self, ref, router=None, request=None, replica=None):
        self._ref = ref
        self._router = router
        self._request = request  # (method, args, kwargs) PRE-resolution
        self._replica = replica  # the actor handle this attempt targets

    def result(self, timeout: Optional[float] = None, *, timeout_s: Optional[float] = None) -> Any:
        # timeout_s: the reference's spelling (serve.handle.DeploymentResponse)
        import time as _time

        budget = timeout_s if timeout_s is not None else timeout
        deadline = None if budget is None else _time.monotonic() + budget
        while True:
            try:
                remaining = None if deadline is None else max(0.01, deadline - _time.monotonic())
                value = ray_tpu.get(self._ref, timeout=remaining)
                # retries are pointless after success: drop the replay
                # payload so the response doesn't pin args/router forever
                self._router = self._request = self._replica = None
                return value
            except Exception as exc:  # noqa: BLE001 — filtered below
                from ray_tpu.exceptions import (
                    DeadlineExceededError,
                    OverloadedError,
                    StoreFullError,
                    raised_copy,
                )
                from ray_tpu.runtime.admission import unwrap

                cause = unwrap(exc)
                if cause is not exc and isinstance(
                    cause, (OverloadedError, DeadlineExceededError, StoreFullError)
                ):
                    # typed admission/deadline signals raised INSIDE a
                    # replica cross the actor boundary wrapped in
                    # RayTaskError; the handle contract is the typed error
                    # itself (the proxy maps it to 429/503/504)
                    raise raised_copy(cause) from None
                if (
                    self._router is None
                    or self._request is None
                    or not _is_system_failure(exc)
                    or not self._router._idempotent
                    or (deadline is not None and _time.monotonic() >= deadline)
                ):
                    raise
                if self._replica is not None:
                    self._router.report_dead(self._replica)
                    self._replica = None
                method, args, kwargs = self._request
                retry = self._router.route_within(
                    method, args, kwargs,
                    deadline=deadline if deadline is not None else _time.monotonic() + 30.0,
                )
                if retry is None:
                    raise  # no usable membership before the deadline
                self._ref, self._replica = retry._ref, retry._replica

    def _to_object_ref(self):
        return self._ref


class _DisaggResponse:
    """Future-like response for the disaggregated path: the dispatcher
    drives prefill → migration → decode on a router worker thread; this
    wraps its future with the DeploymentResponse surface (``result()`` with
    the reference's ``timeout_s`` spelling, typed admission errors
    unwrapped, ``_to_object_ref`` for composition)."""

    def __init__(self, fut):
        self._fut = fut

    def result(self, timeout: Optional[float] = None, *,
               timeout_s: Optional[float] = None) -> Any:
        budget = timeout_s if timeout_s is not None else timeout
        try:
            return self._fut.result(budget)
        except Exception as exc:  # noqa: BLE001 — filtered below
            from ray_tpu.exceptions import (
                DeadlineExceededError,
                OverloadedError,
                StoreFullError,
                raised_copy,
            )
            from ray_tpu.runtime.admission import unwrap

            cause = unwrap(exc)
            if cause is not exc and isinstance(
                cause, (OverloadedError, DeadlineExceededError, StoreFullError)
            ):
                raise raised_copy(cause) from None
            raise

    def _to_object_ref(self):
        return ray_tpu.put(self.result())


class Router:
    def __init__(self, deployment_name: str, controller_handle):
        self.deployment_name = deployment_name
        self.controller = controller_handle
        self._replicas: List[Any] = []
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        # bounded router queue (max_queued_requests >= 0): requests beyond
        # the replicas' aggregate concurrency WAIT here for a replica to
        # free (notified by completions/membership), bounded by the queue
        # cap — replicas are never overcommitted, overflow sheds typed
        self._cv = threading.Condition(self._lock)
        self._queue_waiters = 0
        self._version = -1
        self._rng = random.Random()
        self._reqs_since_push = 0
        self._watching = False
        self._metric_tags = {"deployment": deployment_name}
        # per-deployment series: two bounded deployments must not
        # clobber each other's admission-depth gauge
        self._depth_tags = {"layer": "router", "deployment": deployment_name}
        # per-deployment admission/retry knobs (controller.get_deployment
        # _meta), refreshed on membership changes — never per request
        self._max_ongoing = 100
        self._max_queued = -1
        self._idempotent = False
        self._meta_version = None
        # disaggregated prefill/decode (serve/disagg.py): roles declared by
        # the deployment, the per-replica role list (index-aligned with
        # _replicas per membership version), and the lazily-built dispatcher
        # + its dispatch pool (dispatcher calls block on prefill AND decode,
        # so they run off the caller's thread to keep .remote() non-blocking)
        self._roles: Optional[Dict[str, int]] = None
        self._replica_roles: List[str] = []
        self._disagg = None
        self._disagg_pool = None

    # ------------------------------------------------------------ updates
    def _apply_snapshot(self, version: int, replicas: List[Any]) -> None:
        with self._lock:
            if version != self._version:
                self._version = version
                self._replicas = replicas
                # identity-keyed: surviving replicas KEEP their in-flight
                # counts across membership changes — zeroing (or index
                # shifts) would let the bounded-admission path over-dispatch
                # onto still-saturated survivors after a replica death
                self._inflight = {
                    id(r): self._inflight.get(id(r), 0) for r in replicas
                }
                self._cv.notify_all()  # queued requests re-evaluate membership

    def _refresh(self, force: bool = False) -> None:
        # Membership updates arrive via a long-poll watcher (parity:
        # LongPollHost, serve/_private/long_poll.py); the synchronous pull
        # only runs before the first snapshot lands.
        # rt-lint: disable=lock-discipline -- double-checked lazy start:
        # the unlocked read is a fast path; the decision re-runs under
        # _lock before the watcher thread is spawned
        if not self._watching:
            with self._lock:
                if self._watching:
                    return
                self._watching = True
            threading.Thread(
                target=self._watch_loop, daemon=True, name=f"serve-watch-{self.deployment_name}"
            ).start()
        # rt-lint: disable=lock-discipline -- bootstrap emptiness probe: a
        # stale read costs one redundant pull; _apply_snapshot is
        # version-gated so a racing watcher update always wins
        if force or not self._replicas:
            version, replicas = ray_tpu.get(self.controller.get_replicas.remote(self.deployment_name))
            self._apply_snapshot(version, replicas)
            self._refresh_meta()

    def _refresh_meta(self) -> None:
        """Pull the deployment's admission/retry knobs once per membership
        version (a redeploy may change them; requests must not)."""
        with self._lock:
            if self._meta_version == self._version:
                return
            version = self._version
        try:
            meta = ray_tpu.get(
                self.controller.get_deployment_meta.remote(self.deployment_name),
                timeout=10,
            )
        except Exception:  # noqa: BLE001 — keep the last-known knobs
            return
        with self._lock:
            if meta:
                self._max_ongoing = int(meta.get("max_ongoing_requests", 100))
                self._max_queued = int(meta.get("max_queued_requests", -1))
                self._idempotent = bool(meta.get("idempotent", False))
                self._roles = meta.get("roles") or None
                self._replica_roles = list(meta.get("replica_roles") or ())
            self._meta_version = version

    def _watch_loop(self) -> None:
        import time

        failures = 0
        while failures < 3:
            try:
                # rt-lint: disable=lock-discipline -- a stale _version just
                # long-polls with an old cursor: the reply is re-applied
                # through the version-gated _apply_snapshot, a no-op repeat
                version, replicas = ray_tpu.get(
                    self.controller.poll_replicas.remote(self.deployment_name, self._version, 5.0),
                    timeout=30,
                )
                failures = 0
                self._apply_snapshot(version, replicas)
                self._refresh_meta()
            except Exception:
                failures += 1
                time.sleep(0.5)
        # controller unreachable: stand down; the next route() restarts us
        with self._lock:
            self._watching = False

    # ------------------------------------------------------------ routing
    def report_dead(self, replica) -> None:
        """A caller observed this replica fail: prune it locally NOW — the
        controller's snapshot keeps listing it for up to a health-check
        period, and re-routing onto it just burns the retry."""
        with self._lock:
            if replica in self._replicas:
                # prune the role entry at the same index so _replica_roles
                # stays aligned until the controller's replacement snapshot
                idx = next(
                    i for i, r in enumerate(self._replicas) if r is replica
                )
                if idx < len(self._replica_roles):
                    self._replica_roles = (
                        self._replica_roles[:idx] + self._replica_roles[idx + 1:]
                    )
                self._replicas = [r for r in self._replicas if r is not replica]
                self._inflight = {
                    id(r): self._inflight.get(id(r), 0) for r in self._replicas
                }
                self._cv.notify_all()

    def route_within(self, method: str, args: tuple, kwargs: dict, *, deadline: float):
        """route(), but wait for usable membership (a live replica) up to
        ``deadline`` instead of failing fast; None if none appeared."""
        import time as _time

        while True:
            try:
                return self.route(method, args, kwargs)
            except RuntimeError:
                if _time.monotonic() >= deadline:
                    return None
                _time.sleep(0.1)
                self._refresh(force=True)

    def _load_locked(self, idx: int) -> int:
        return self._inflight.get(id(self._replicas[idx]), 0)

    def _pick_free_locked(self) -> Optional[int]:
        """Pow-2 choice restricted to replicas below ``max_ongoing``; falls
        back to the global minimum when the sample is saturated.  None =
        every replica is at capacity (the caller queues or sheds)."""
        n = len(self._replicas)
        if n == 0:
            return None
        cap = max(1, self._max_ongoing)
        if n == 1:
            idx = 0
        else:
            a, b = self._rng.sample(range(n), 2)
            idx = a if self._load_locked(a) <= self._load_locked(b) else b
        if self._load_locked(idx) >= cap:
            idx = min(range(n), key=self._load_locked)
            if self._load_locked(idx) >= cap:
                return None
        return idx

    def _admit_bounded_locked(self) -> int:
        """Bounded-queue admission (max_queued_requests >= 0, reference
        ``max_queued_requests`` parity): replicas are never dispatched past
        ``max_ongoing`` — a request arriving with every replica saturated
        WAITS here (counted as the router queue, gauge-visible) until a
        completion frees a slot; arrivals beyond the queue bound shed with
        the typed 429 signal.  Called under ``self._lock``."""
        # newcomers defer to already-queued requests: a fresh arrival must
        # not barge past waiters onto a just-freed slot (CPython Condition
        # wakes waiters in arrival order, so with this gate admission is
        # near-FIFO and a long-waiting request cannot be starved into its
        # queue_timeout by a stream of later arrivals)
        if self._queue_waiters == 0:
            idx = self._pick_free_locked()
            if idx is not None:
                return idx
        if self._queue_waiters >= self._max_queued:
            from ray_tpu.runtime import admission

            raise admission.shed(
                "router", "queue_full",
                message=(
                    f"deployment {self.deployment_name!r}: every replica at "
                    f"max_ongoing_requests ({self._max_ongoing}) and "
                    f"{self._queue_waiters} requests already queued "
                    f"(max_queued_requests {self._max_queued})"
                ),
            )
        self._queue_waiters += 1
        metric_defs.ADMISSION_QUEUE_DEPTH.set(self._queue_waiters, self._depth_tags)
        from ray_tpu.core.config import get_config

        deadline = time.monotonic() + get_config().router_queue_wait_timeout_s
        try:
            while True:
                # short timed waits so membership flaps can't strand us.
                # Transiently-EMPTY membership (replica died, controller
                # replacing it) keeps waiting within the budget — the rest
                # of the failover machinery (route_within) does the same;
                # failing every queued request the instant a replica dies
                # would turn a ~1s replacement into a burst of 500s.
                self._cv.wait(0.05)
                if self._replicas:
                    idx = self._pick_free_locked()
                    if idx is not None:
                        return idx
                if time.monotonic() >= deadline:
                    if not self._replicas:
                        raise RuntimeError(
                            f"deployment {self.deployment_name!r} has no replicas"
                        )
                    # a wedged replica must cost a typed 429, not a handle
                    # call that never returns
                    from ray_tpu.runtime import admission

                    raise admission.shed(
                        "router", "queue_timeout",
                        message=(
                            f"deployment {self.deployment_name!r}: no "
                            "replica slot freed within "
                            "router_queue_wait_timeout_s"
                        ),
                    )
        finally:
            self._queue_waiters -= 1
            metric_defs.ADMISSION_QUEUE_DEPTH.set(
                self._queue_waiters, self._depth_tags
            )

    # --------------------------------------------- disaggregated dispatch
    def call_replica(self, deployment: str, index: int, method: str,
                     args: tuple, tenant=None, trace=None, *,
                     timeout: Optional[float] = None):
        """Call ONE replica by index and block for its result (the disagg
        dispatcher's primitive: migrations target a specific replica pair,
        so pow-2 sampling happens in pick_role_replica, not here).  The
        in-flight count still settles through the completion hook so the
        queue-depth signal sees dispatcher traffic too."""
        with self._lock:
            if index < 0 or index >= len(self._replicas):
                raise RuntimeError(
                    f"deployment {deployment!r} replica #{index} left the "
                    "membership (died or scaled away)"
                )
            replica = self._replicas[index]
            rkey = id(replica)
            self._inflight[rkey] = self._inflight.get(rkey, 0) + 1
        ref = replica.handle_request.remote(
            method, tuple(args), {}, tenant, trace
        )
        from ray_tpu.api import get_cluster

        get_cluster().directory.wait_for(
            ref.id(), lambda _node, k=rkey: self._request_finished(k)
        )
        return ray_tpu.get(ref, timeout=timeout)

    def pick_role_replica(self, deployment: str, role: str,
                          signal: str = "queue") -> int:
        """Pick a replica index from one role's pool.  ``signal="queue"``
        (prefill): pow-2 over locally-tracked in-flight counts — prefill is
        compute-bound, so queue depth is the contended resource.
        ``signal="kv_free"`` (decode): probe free KV pages on a pow-2
        sample and take the roomier replica — decode is HBM-bound, and a
        migration landing on a page-starved replica just sheds."""
        self._refresh()
        with self._lock:
            if len(self._replica_roles) != len(self._replicas):
                aligned = False
            else:
                aligned = True
            roles = list(self._replica_roles)
            n = len(self._replicas)
        if not aligned:
            self._refresh(force=True)
            with self._lock:
                roles = list(self._replica_roles)
                n = len(self._replicas)
        idxs = [i for i, r in enumerate(roles[:n]) if r == role]
        if not idxs:
            raise RuntimeError(
                f"deployment {deployment!r} has no live {role!r} replicas"
            )
        if len(idxs) == 1:
            return idxs[0]
        with self._lock:
            a, b = self._rng.sample(idxs, 2)
        if signal == "kv_free":
            best, best_free = a, -1
            for i in (a, b):
                try:
                    free = int(self.call_replica(
                        deployment, i, "kv_free_blocks", (), timeout=5.0
                    ))
                except Exception:  # noqa: BLE001 — probe failure = skip
                    continue
                if free > best_free:
                    best, best_free = i, free
            return best
        with self._lock:
            return a if self._load_locked(a) <= self._load_locked(b) else b

    def _route_disagg(self, args: tuple, kwargs: dict) -> "_DisaggResponse":
        """Delegate a ``__call__`` on a roles deployment to the disagg
        dispatcher (prefill pool → KV migration → decode pool) on a worker
        thread, so ``.remote()`` stays non-blocking like ordinary dispatch."""
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu.runtime.context import current_request_trace, current_tenant
        from ray_tpu.serve.disagg import DisaggDispatcher

        trace = current_request_trace()
        if trace is not None:
            trace.mark("router_in")
            if not trace.deployment:
                trace.deployment = self.deployment_name
        request = args[0] if args else kwargs.get("request")
        if not isinstance(request, dict):
            raise TypeError(
                f"disaggregated deployment {self.deployment_name!r} takes a "
                "single request dict"
            )
        tenant = current_tenant()
        with self._lock:
            if self._disagg is None:
                self._disagg = DisaggDispatcher(self, self.deployment_name)
            if self._disagg_pool is None:
                self._disagg_pool = ThreadPoolExecutor(
                    max_workers=32,
                    thread_name_prefix=f"disagg-{self.deployment_name}",
                )
            disp, pool = self._disagg, self._disagg_pool
        metric_defs.SERVE_ROUTER_REQUESTS.inc(tags=self._metric_tags)
        if trace is not None:
            trace.mark("router_dequeue")
        return _DisaggResponse(pool.submit(disp.route, request, tenant, trace))

    def disagg_snapshot(self) -> Optional[dict]:
        """Per-role dispatch/migration counters for rt llm / /api/overload
        (None until the first disaggregated request)."""
        with self._lock:
            disp = self._disagg
        return None if disp is None else disp.snapshot()

    def route(self, method: str, args: tuple, kwargs: dict) -> DeploymentResponse:
        from ray_tpu.runtime.context import current_request_trace, current_tenant

        t_start = time.perf_counter()
        trace = current_request_trace()
        if trace is not None:
            trace.mark("router_in")
            if not trace.deployment:
                trace.deployment = self.deployment_name
        # rt-lint: disable=lock-discipline -- emptiness fast-path only: it
        # decides refresh-or-fail; replica SELECTION below holds _lock
        if not self._replicas:
            self._refresh()
        if not self._replicas:  # rt-lint: disable=lock-discipline -- same
            raise RuntimeError(f"deployment {self.deployment_name!r} has no replicas")
        # rt-lint: disable=lock-discipline -- meta-gated delegation: _roles
        # only transitions None->dict at meta refresh; a stale None routes
        # one early request homogeneously, never corrupts state
        if self._roles and method == "__call__":
            # roles deployment: __call__ takes the disaggregated path
            # (prefill pool -> KV migration -> decode pool); other methods
            # (stats, reconfigure hooks) still dispatch normally below
            return self._route_disagg(args, kwargs)
        original_request = (method, args, kwargs)  # PRE-resolution, for replay
        tenant = current_tenant()
        with self._lock:
            if self._max_queued >= 0:
                idx = self._admit_bounded_locked()
            elif len(self._replicas) == 1:
                idx = 0
            else:
                # power of two choices over locally-tracked in-flight counts
                a, b = self._rng.sample(range(len(self._replicas)), 2)
                idx = a if self._load_locked(a) <= self._load_locked(b) else b
            replica = self._replicas[idx]
            rkey = id(replica)
            self._inflight[rkey] = self._inflight.get(rkey, 0) + 1
            total_inflight = sum(self._inflight.values())
            self._reqs_since_push += 1
            push = self._reqs_since_push >= 10
            if push:
                self._reqs_since_push = 0
        metric_defs.SERVE_ROUTER_REQUESTS.inc(tags=self._metric_tags)
        metric_defs.SERVE_ROUTER_INFLIGHT.set(total_inflight, self._metric_tags)
        from ray_tpu.runtime.admission import tenant_tags

        metric_defs.TENANT_ADMISSIONS.inc(tags=tenant_tags(tenant))
        metric_defs.SERVE_ROUTER_QUEUE_WAIT.observe(
            time.perf_counter() - t_start, tags=self._metric_tags
        )
        if trace is not None:
            trace.mark("router_dequeue")
        # Resolve nested DeploymentResponses: pass their refs so the fabric
        # chains the calls without blocking here (model composition).
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse) else a for a in args)
        kwargs = {k: (v._to_object_ref() if isinstance(v, DeploymentResponse) else v) for k, v in kwargs.items()}
        # the trace rides as an explicit argument, like the tenant —
        # contextvars do not survive the actor-call boundary (replicas run
        # requests on pool threads)
        ref = replica.handle_request.remote(method, args, kwargs, tenant, trace)
        # Ready-hook, not ref.future(): a future would pull every response
        # onto the router's node; the directory callback fires when the
        # result is committed anywhere, without materializing it here.
        from ray_tpu.api import get_cluster

        get_cluster().directory.wait_for(
            ref.id(), lambda _node, k=rkey: self._request_finished(k)
        )
        if push:
            self._push_metrics()
        return DeploymentResponse(ref, router=self, request=original_request, replica=replica)

    def _push_metrics(self) -> None:
        try:
            # rt-lint: disable=lock-discipline -- metrics snapshot: the
            # copy races membership swaps by design; a rare mid-resize
            # RuntimeError lands in the except and drops one push
            self.controller.record_request_metrics.remote(
                self.deployment_name, dict(self._inflight)
            )
        except Exception:
            pass

    def _request_finished(self, rkey: int) -> None:
        with self._lock:
            if rkey in self._inflight and self._inflight[rkey] > 0:
                self._inflight[rkey] -= 1
            total_inflight = sum(self._inflight.values())
            drained = not total_inflight
            if self._queue_waiters:
                self._cv.notify()  # a queued request can dispatch now
        metric_defs.SERVE_ROUTER_INFLIGHT.set(total_inflight, self._metric_tags)
        if drained:
            # without this push the controller's last snapshot would show
            # ongoing requests forever and it would never scale down
            self._push_metrics()

    def stale(self) -> bool:
        return True


# One Router (and thus one long-poll watcher thread) per deployment per
# controller — handles are created freely (serve.run makes one per
# sub-deployment per call) and must not each spawn a watcher.
_router_cache: Dict[tuple, "Router"] = {}
_router_cache_lock = threading.Lock()


def _shared_router(deployment_name: str, controller_handle) -> "Router":
    key = (id(controller_handle), deployment_name)
    with _router_cache_lock:
        router = _router_cache.get(key)
        if router is None:
            router = _router_cache[key] = Router(deployment_name, controller_handle)
        return router


def clear_router_cache() -> None:
    """Called on serve.shutdown so stale watchers drain and a new serve
    instance gets fresh routers."""
    with _router_cache_lock:
        _router_cache.clear()


class DeploymentHandle:
    """What users (and the proxy) call (parity: serve DeploymentHandle)."""

    def __init__(self, deployment_name: str, controller_handle):
        self.deployment_name = deployment_name
        self._router = _shared_router(deployment_name, controller_handle)
        self._method = "__call__"

    def options(self, *, method_name: Optional[str] = None) -> "DeploymentHandle":
        h = DeploymentHandle.__new__(DeploymentHandle)
        h.deployment_name = self.deployment_name
        h._router = self._router
        h._method = method_name or self._method
        return h

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._router._refresh()
        return self._router.route(self._method, args, kwargs)

    def __getattr__(self, name: str) -> "_MethodCaller":
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._handle._router._refresh()
        return self._handle._router.route(self._method, args, kwargs)
