"""ray_tpu.util: utility APIs layered on the core.

Parity: ``python/ray/util/`` (SURVEY §2.4 util misc) — ActorPool, Queue,
collective ops, scheduling strategies, serializability checking.
"""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util.check_serialize import inspect_serializability
from ray_tpu.util.placement import (
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.runtime.scheduler import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "ActorPool",
    "PlacementGroup",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
    "Empty",
    "Full",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "Queue",
    "inspect_serializability",
]
