"""ray_tpu.util: utility APIs layered on the core.

Parity: ``python/ray/util/`` (SURVEY §2.4 util misc) — ActorPool, Queue,
collective ops, scheduling strategies, serializability checking.
"""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util.check_serialize import inspect_serializability
from ray_tpu.util import collective, iter, pdb  # noqa: A004 — reference name
from ray_tpu.util import pdb as ray_debugpy  # reference exports util.debugpy under this name
from ray_tpu.util.client.worker import connect
from ray_tpu.util.misc import (
    deregister_serializer,
    disable_log_once_globally,
    enable_periodic_logging,
    get_node_ip_address,
    list_named_actors,
    log_once,
    register_serializer,
)
from ray_tpu.util.placement import (
    PlacementGroup,
    get_current_placement_group,
    get_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.runtime.scheduler import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from ray_tpu import accelerators


def disconnect() -> None:
    """Close the thin-client session opened by util.connect (parity:
    ray.util.disconnect — connect() returns the context; keeping a module
    handle on the last one mirrors the reference's global stub)."""
    ctx = getattr(connect, "_last_context", None)
    if ctx is not None:
        ctx.disconnect()


__all__ = [
    "ActorPool",
    "PlacementGroup",
    "accelerators",
    "collective",
    "connect",
    "disconnect",
    "get_current_placement_group",
    "get_placement_group",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
    "Empty",
    "Full",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "Queue",
    "deregister_serializer",
    "disable_log_once_globally",
    "enable_periodic_logging",
    "get_node_ip_address",
    "inspect_serializability",
    "iter",
    "list_named_actors",
    "log_once",
    "pdb",
    "ray_debugpy",
    "register_serializer",
]
