"""Small ray.util parity helpers: node IP, named-actor listing, custom
serializers, and the log-once/periodic-logging switches.

Parity anchors: python/ray/_private/services.py (get_node_ip_address),
python/ray/util/__init__.py (list_named_actors),
python/ray/util/serialization.py (register/deregister_serializer),
python/ray/util/debug.py (log_once / disable_log_once_globally /
enable_periodic_logging).
"""

from __future__ import annotations

import copyreg
import socket
import time
from typing import Any, Callable, Dict, List


def reserve_port(host: str = "127.0.0.1") -> socket.socket:
    """Bind an ephemeral port and return the OPEN socket.

    The caller closes it when whatever service will actually own the port
    is ready to bind — holding the socket open prevents the kernel handing
    the same port to a concurrent caller (the flaw in probe-and-close
    helpers: two gang ranks on one host can otherwise collide)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    return s


def free_port(host: str = "127.0.0.1") -> int:
    """Probe-and-close ephemeral port lookup.  Only for single-caller uses
    (e.g. one driver picking a master port); concurrent callers should hold
    ``reserve_port`` sockets through their rendezvous instead."""
    s = reserve_port(host)
    try:
        return s.getsockname()[1]
    finally:
        s.close()


def get_node_ip_address() -> str:
    """This host's primary outbound IP (no traffic is sent: a UDP connect
    just selects the route)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def list_named_actors(all_namespaces: bool = False) -> List[Any]:
    """Names of all live named actors (parity: util.list_named_actors)."""
    from ray_tpu.api import get_cluster

    cluster = get_cluster()
    out = []
    for info in cluster.control.actors.list_actors():
        if info.name and info.state.name not in ("DEAD",):
            if all_namespaces:
                out.append({"name": info.name, "namespace": getattr(info, "namespace", "default")})
            else:
                out.append(info.name)
    return out


# ------------------------------------------------------------- serializers
_custom_serializers: Dict[type, tuple] = {}


def register_serializer(cls: type, *, serializer: Callable, deserializer: Callable) -> None:
    """Install a custom (de)serializer for ``cls`` on every pickle path
    (parity: util.register_serializer).  Implemented via copyreg, so it
    applies to the control plane, the data plane, and worker IPC alike —
    workers registered the same way decode symmetrically."""

    def reduce_fn(obj):
        return (_deserialize_custom, (cls.__module__, cls.__qualname__, serializer(obj)))

    _custom_serializers[cls] = (serializer, deserializer)
    copyreg.pickle(cls, reduce_fn)


def deregister_serializer(cls: type) -> None:
    _custom_serializers.pop(cls, None)
    copyreg.dispatch_table.pop(cls, None)


def _deserialize_custom(module: str, qualname: str, payload):
    import importlib

    cls = importlib.import_module(module)
    for part in qualname.split("."):
        cls = getattr(cls, part)
    entry = _custom_serializers.get(cls)
    if entry is None:
        raise TypeError(
            f"no serializer registered for {module}.{qualname} in this "
            f"process — call util.register_serializer here too (the "
            f"registration is per-process, like the reference's)"
        )
    return entry[1](payload)


# ------------------------------------------------------------ log controls
_log_once_seen: set = set()
_log_once_disabled = False
_periodic_s = 0.0
_last_logged: Dict[str, float] = {}


def log_once(key: str) -> bool:
    """True the first time ``key`` is seen (or once per period when
    periodic logging is enabled); the caller does the actual logging
    (parity: util.debug.log_once)."""
    if _log_once_disabled:
        return False
    now = time.monotonic()
    if _periodic_s > 0:
        if now - _last_logged.get(key, -1e18) >= _periodic_s:
            _last_logged[key] = now
            return True
        return False
    if key in _log_once_seen:
        return False
    _log_once_seen.add(key)
    _last_logged[key] = now
    return True


def disable_log_once_globally() -> None:
    global _log_once_disabled
    _log_once_disabled = True


def enable_periodic_logging(period_s: float = 60.0) -> None:
    """log_once keys re-fire every ``period_s`` instead of never again."""
    global _periodic_s
    _periodic_s = period_s
