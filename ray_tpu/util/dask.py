"""Dask-on-ray_tpu: execute dask task graphs on the cluster's task fabric.

Parity: ``python/ray/util/dask/scheduler.py`` (``ray_dask_get`` — the
drop-in dask scheduler that turns every graph node into a submitted task,
with dependencies passed as object refs so the fabric handles ordering and
locality) and ``python/ray/util/dask/__init__.py`` (``enable_dask_on_ray``
config hook).

A dask graph is plain data — ``{key: literal | key | (callable, *args)}``
with keys referenced anywhere inside task args — so the scheduler itself
has no dask dependency at all; only ``enable_dask_on_ray`` (which flips
``dask.config``) needs dask importable.  That means graphs hand-built or
produced by any dask collection run unmodified.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Set

import ray_tpu

__all__ = [
    "ray_dask_get",
    "ray_dask_get_sync",
    "enable_dask_on_ray",
    "disable_dask_on_ray",
]

_DEP = "__rt_dask_dep__"


def _istask(x: Any) -> bool:
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _find_deps(comp: Any, dsk_keys, out: Set[Hashable]) -> None:
    """Collect graph keys referenced anywhere inside a computation.

    Mirrors ``dask.core.get_dependencies``: inside a task tuple, any value
    that *is* a key of the graph is a reference to it; lists/dicts recurse.
    """
    if _istask(comp):
        for arg in comp[1:]:
            _find_deps(arg, dsk_keys, out)
        return
    try:
        if comp in dsk_keys:
            out.add(comp)
            return
    except TypeError:
        pass  # unhashable literal (list/dict) — recurse below
    if isinstance(comp, list):
        for item in comp:
            _find_deps(item, dsk_keys, out)
    elif isinstance(comp, dict):
        for item in comp.values():
            _find_deps(item, dsk_keys, out)


def _rewrite(comp: Any, dep_index: Dict[Hashable, int]) -> Any:
    """Replace key references with positional markers resolved in-task."""
    if _istask(comp):
        return (comp[0],) + tuple(_rewrite(a, dep_index) for a in comp[1:])
    try:
        if comp in dep_index:
            return (_DEP, dep_index[comp])
    except TypeError:
        pass
    if isinstance(comp, list):
        return [_rewrite(item, dep_index) for item in comp]
    if isinstance(comp, dict):
        return {k: _rewrite(v, dep_index) for k, v in comp.items()}
    return comp


def _evaluate(comp: Any, deps: tuple) -> Any:
    if isinstance(comp, tuple) and len(comp) == 2 and comp[0] == _DEP:
        return deps[comp[1]]
    if _istask(comp):
        return comp[0](*[_evaluate(a, deps) for a in comp[1:]])
    if isinstance(comp, list):
        return [_evaluate(item, deps) for item in comp]
    if isinstance(comp, dict):
        return {k: _evaluate(v, deps) for k, v in comp.items()}
    return comp


def _toposort(dsk: Dict[Hashable, Any]):
    """Returns (execution order, {key: dependency set})."""
    deps: Dict[Hashable, Set[Hashable]] = {}
    keys = dsk.keys()
    for k, comp in dsk.items():
        found: Set[Hashable] = set()
        _find_deps(comp, keys, found)
        found.discard(k)
        deps[k] = found
    order: List[Hashable] = []
    state: Dict[Hashable, int] = {}  # 1 = visiting, 2 = done
    for root in dsk:
        if state.get(root) == 2:
            continue
        stack: List[tuple] = [(root, False)]
        while stack:
            k, children_done = stack.pop()
            if children_done:
                state[k] = 2
                order.append(k)
                continue
            if state.get(k) == 2:
                continue
            if state.get(k) == 1:
                raise ValueError(f"cycle in dask graph through key {k!r}")
            state[k] = 1
            stack.append((k, True))
            for d in sorted(deps[k], key=repr, reverse=True):
                if state.get(d) != 2:
                    stack.append((d, False))
    return order, deps


def _unpack(keys: Any, values: Dict[Hashable, Any]) -> Any:
    """Match dask's get contract: nested key lists map to nested results."""
    if isinstance(keys, list):
        return [_unpack(k, values) for k in keys]
    return values[keys]


_NODE_TASK = None


def _node_task():
    """The shared graph-node remote function, created once per process
    (re-registering it per scheduler call wastes export overhead)."""
    global _NODE_TASK
    if _NODE_TASK is None:

        @ray_tpu.remote
        def _dask_node(spec, *dep_vals):
            return _evaluate(spec, dep_vals)

        _NODE_TASK = _dask_node
    return _NODE_TASK


def ray_dask_get(dsk: Dict[Hashable, Any], keys: Any, *, ray_persist: bool = False, **_: Any) -> Any:
    """Dask scheduler: one submitted task per graph node.

    Dependencies flow as object refs, so independent branches execute
    concurrently on the fabric and data stays in the object store between
    nodes.  ``keys`` may be a single key or arbitrarily nested lists of
    keys (dask collections pass nested lists); ``ray_persist=True`` returns
    refs instead of materialized values (parity: scheduler.py's persist
    path).
    """
    node = _node_task()
    refs: Dict[Hashable, Any] = {}
    order, deps = _toposort(dsk)
    for k in order:
        ordered = sorted(deps[k], key=repr)
        dep_index = {d: i for i, d in enumerate(ordered)}
        spec = _rewrite(dsk[k], dep_index)
        refs[k] = node.remote(spec, *[refs[d] for d in ordered])
    if ray_persist:
        return _unpack(keys, refs)
    flat: List[Hashable] = []

    def _flatten(ks):
        if isinstance(ks, list):
            for x in ks:
                _flatten(x)
        else:
            flat.append(ks)

    _flatten(keys)
    values = dict(zip(flat, ray_tpu.get([refs[k] for k in flat])))
    return _unpack(keys, values)


def ray_dask_get_sync(dsk: Dict[Hashable, Any], keys: Any, **_: Any) -> Any:
    """Serial in-process variant (parity: scheduler.py ray_dask_get_sync) —
    the debugging scheduler: no tasks submitted, plain topological eval."""
    values: Dict[Hashable, Any] = {}
    order, deps = _toposort(dsk)
    for k in order:
        ordered = sorted(deps[k], key=repr)
        dep_index = {d: i for i, d in enumerate(ordered)}
        spec = _rewrite(dsk[k], dep_index)
        values[k] = _evaluate(spec, tuple(values[d] for d in ordered))
    return _unpack(keys, values)


def enable_dask_on_ray() -> None:
    """Make ray_dask_get dask's default scheduler (needs dask installed)."""
    try:
        import dask
    except ImportError as exc:
        raise ImportError(
            "enable_dask_on_ray() needs dask installed (`pip install dask`). "
            "ray_dask_get/ray_dask_get_sync work on raw graphs without it."
        ) from exc
    dask.config.set(scheduler=ray_dask_get)


def disable_dask_on_ray() -> None:
    try:
        import dask
    except ImportError as exc:
        raise ImportError("dask is not installed") from exc
    dask.config.set(scheduler=None)
