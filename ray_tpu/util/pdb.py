"""Breakpoint helper for tasks/actors (parity role: ray.util.pdb
set_trace + the ray debugger, python/ray/util/debugpy.py).

The reference attaches a remote debugpy session to the worker process.
Here the common execution tiers (inproc/thread) share the driver's
terminal, so a plain pdb attaches directly when stdin is a TTY; in a
process worker (no usable TTY) the breakpoint is skipped with a logged
warning instead of hanging the worker forever on an unreadable stdin.
"""

from __future__ import annotations

import pdb as _pdb
import sys


def set_trace(breakpoint_uuid=None):
    """Drop into pdb if this process can actually interact; no-op (with a
    warning) in non-interactive workers."""
    if sys.stdin is not None and sys.stdin.isatty():
        debugger = _pdb.Pdb()
        debugger.set_trace(sys._getframe().f_back)
        return
    print(
        "ray_tpu.util.pdb.set_trace(): skipped — this worker has no "
        "interactive stdin (run the task with execution='inproc' to debug "
        "on the driver's terminal)",
        file=sys.stderr,
        flush=True,
    )
