"""User-facing metrics API (parity: ``ray.util.metrics`` — Counter/Gauge/
Histogram that application code defines and the runtime exports through the
same Prometheus endpoint as the system metrics)."""

from ray_tpu.observability.metrics import global_registry


def Counter(name: str, description: str = "", tag_keys=None):
    return global_registry().counter(name, description)


def Gauge(name: str, description: str = "", tag_keys=None):
    return global_registry().gauge(name, description)


def Histogram(name: str, description: str = "", boundaries=None, tag_keys=None):
    return global_registry().histogram(name, description, boundaries=tuple(boundaries or ()))


__all__ = ["Counter", "Gauge", "Histogram"]
