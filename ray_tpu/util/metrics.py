"""User-facing metrics API (parity: ``ray.util.metrics`` — Counter/Gauge/
Histogram that application code defines and the runtime exports through the
same Prometheus endpoint as the system metrics; ``python/ray/util/metrics.py``
Metric.set_default_tags :104).

Thin wrappers over the shared registry: default tags set once merge under
per-record tags, exactly like the reference."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from ray_tpu.observability.metrics import global_registry


class _UserMetric:
    def __init__(self, metric, tag_keys: Optional[Sequence[str]] = None):
        self._metric = metric
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        """Tags applied to every record unless overridden per call."""
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Optional[Dict[str, str]]:
        merged = {**self._default_tags, **(tags or {})} or None
        if self._tag_keys and merged:
            unknown = set(merged) - set(self._tag_keys)
            if unknown:
                # declared tag_keys are a schema: a typo'd tag must error,
                # not export a stray series (reference Metric.record)
                raise ValueError(
                    f"unknown tag(s) {sorted(unknown)}; declared tag_keys "
                    f"are {list(self._tag_keys)}"
                )
        return merged

    @property
    def info(self) -> dict:
        return {
            "name": self._metric.name,
            "description": self._metric.description,
            "tag_keys": self._tag_keys,
            "default_tags": dict(self._default_tags),
        }


class Counter(_UserMetric):
    """Monotonic counter (parity: ray.util.metrics.Counter)."""

    def __init__(self, name: str, description: str = "", tag_keys: Optional[Sequence[str]] = None):
        super().__init__(global_registry().counter(name, description), tag_keys)

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> None:
        if value <= 0:
            raise ValueError(f"Counter.inc requires value > 0, got {value}")
        self._metric.inc(value, tags=self._merged(tags))


class Gauge(_UserMetric):
    """Point-in-time value (parity: ray.util.metrics.Gauge)."""

    def __init__(self, name: str, description: str = "", tag_keys: Optional[Sequence[str]] = None):
        super().__init__(global_registry().gauge(name, description), tag_keys)

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        self._metric.set(value, tags=self._merged(tags))


class Histogram(_UserMetric):
    """Distribution with bucket boundaries (parity: ray.util.metrics.Histogram)."""

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[Sequence[float]] = None,
        tag_keys: Optional[Sequence[str]] = None,
    ):
        super().__init__(
            global_registry().histogram(name, description, boundaries=tuple(boundaries or ())),
            tag_keys,
        )

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        self._metric.observe(value, tags=self._merged(tags))


__all__ = ["Counter", "Gauge", "Histogram"]
