"""ray_tpu.util.collective: the public collective-communication API.

Parity: ``python/ray/util/collective/collective.py`` —
``init_collective_group`` :120, ``create_collective_group`` :151,
``allreduce`` :258, ``barrier`` :298, ``broadcast`` :373, ``allgather``
:423, ``reducescatter`` :472, ``send`` :531 / ``recv`` :594 — with the
backend lowered to the TPU fabric instead of NCCL/Gloo: group ops ride the
in-process rendezvous (host actors) and, inside jit, the ``ray_tpu.parallel``
axis collectives (psum/all_gather/ppermute over ICI).

The reference's rendezvous-through-a-named-actor (NCCLUniqueID store)
disappears: groups are fabric-local state, no unique-id exchange needed.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.parallel.collective import (
    _registry,
    allgather_tensor,
    allreduce_tensor,
    broadcast_tensor,
    destroy_collective_group,
    init_collective_group,
    reducescatter_tensor,
)


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: List[int],
    backend: str = "tpu",
    group_name: str = "default",
) -> None:
    """Declarative group creation (reference: collective.py:151) — the driver
    registers the group; actors then call collective ops with their rank."""
    if len(actors) != len(ranks) or len(ranks) != world_size:
        raise ValueError("actors/ranks/world_size mismatch")
    init_collective_group(world_size, ranks[0], backend, group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _registry.get(group_name)
        return True
    except KeyError:
        return False


def get_collective_group_size(group_name: str = "default") -> int:
    return _registry.get(group_name).world_size


# ------------------------------------------------------------------- ops
def allreduce(tensor, group_name: str = "default", op: str = "sum", *, rank: Optional[int] = None):
    return allreduce_tensor(tensor, _need_rank(rank), group_name, op)


def allgather(tensor, group_name: str = "default", *, rank: Optional[int] = None) -> List[Any]:
    return allgather_tensor(tensor, _need_rank(rank), group_name)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default", *, rank: Optional[int] = None):
    return broadcast_tensor(tensor, _need_rank(rank), src_rank, group_name)


def reducescatter(tensor, group_name: str = "default", *, rank: Optional[int] = None):
    return reducescatter_tensor(tensor, _need_rank(rank), group_name)


def barrier(group_name: str = "default", *, rank: Optional[int] = None) -> None:
    allreduce_tensor(0, _need_rank(rank), group_name)


# ---------------------------------------------------------- point-to-point
class _Mailboxes:
    def __init__(self):
        self.lock = threading.Lock()
        self.boxes: Dict[tuple, "_Box"] = {}

    def box(self, group: str, src: int, dst: int) -> "_Box":
        key = (group, src, dst)
        with self.lock:
            if key not in self.boxes:
                self.boxes[key] = _Box()
            return self.boxes[key]


class _Box:
    def __init__(self):
        self.cond = threading.Condition()
        self.items: list = []


_mail = _Mailboxes()


# per-process sequence counters for the cross-process (KV) channel: each
# (group, src, dst) pair is a FIFO stream; the sender numbers messages and
# the receiver consumes them in order
_p2p_send_seq: Dict[tuple, int] = {}
_p2p_recv_seq: Dict[tuple, int] = {}
_p2p_lock = threading.Lock()


def send(tensor, dst_rank: int, group_name: str = "default", *, rank: Optional[int] = None) -> None:
    """Reference: collective.py:531 — point-to-point send.

    Same-process ranks use in-memory mailboxes; across OS processes
    (multi-host fabric) the message rides the cluster KV over the transport."""
    src = _need_rank(rank)
    from ray_tpu.runtime.kv_client import get_kv, is_multiprocess

    if is_multiprocess():
        import pickle

        from ray_tpu.parallel.collective import _host_value

        with _p2p_lock:
            seq = _p2p_send_seq.get((group_name, src, dst_rank), 0)
            _p2p_send_seq[(group_name, src, dst_rank)] = seq + 1
        get_kv().put(
            f"rt_p2p/{group_name}/{src}/{dst_rank}/{seq}".encode(),
            pickle.dumps(_host_value(tensor), protocol=5),
        )
        return
    box = _mail.box(group_name, src, dst_rank)
    with box.cond:
        box.items.append(tensor)
        box.cond.notify_all()


def recv(src_rank: int, group_name: str = "default", *, rank: Optional[int] = None, timeout: float = 120.0):
    """Reference: collective.py:594 — blocking point-to-point receive."""
    dst = _need_rank(rank)
    from ray_tpu.runtime.kv_client import get_kv, is_multiprocess

    if is_multiprocess():
        import pickle
        import time as _time

        with _p2p_lock:
            seq = _p2p_recv_seq.get((group_name, src_rank, dst), 0)
        kv = get_kv()
        key = f"rt_p2p/{group_name}/{src_rank}/{dst}/{seq}".encode()
        deadline = _time.monotonic() + timeout
        while True:
            raw = kv.get(key)
            if raw is not None:
                kv.delete(key)
                # consume the sequence number only on success — a timed-out
                # recv must retry the SAME slot, or the FIFO desyncs
                with _p2p_lock:
                    _p2p_recv_seq[(group_name, src_rank, dst)] = seq + 1
                return pickle.loads(raw)
            if _time.monotonic() > deadline:
                raise TimeoutError(f"recv from rank {src_rank} timed out")
            _time.sleep(0.002)
    box = _mail.box(group_name, src_rank, dst)
    with box.cond:
        ok = box.cond.wait_for(lambda: bool(box.items), timeout=timeout)
        if not ok:
            raise TimeoutError(f"recv from rank {src_rank} timed out")
        return box.items.pop(0)


# ----------------------------------------------------------------- helpers
_rank_local = threading.local()


def set_rank(rank: int) -> None:
    """Bind this thread's rank (actors call once; the reference infers rank
    from the actor registered in the group)."""
    _rank_local.value = rank


def _need_rank(rank: Optional[int]) -> int:
    if rank is not None:
        return rank
    r = getattr(_rank_local, "value", None)
    if r is None:
        raise ValueError("rank not set: pass rank= or call collective.set_rank(rank) first")
    return r


__all__ = [
    "allgather",
    "allreduce",
    "barrier",
    "broadcast",
    "create_collective_group",
    "destroy_collective_group",
    "get_collective_group_size",
    "init_collective_group",
    "is_group_initialized",
    "recv",
    "reducescatter",
    "send",
    "set_rank",
]
