"""ray_tpu.util.collective: the public collective-communication API.

Parity: ``python/ray/util/collective/collective.py`` —
``init_collective_group`` :120, ``create_collective_group`` :151,
``allreduce`` :258, ``barrier`` :298, ``broadcast`` :373, ``allgather``
:423, ``reducescatter`` :472, ``send`` :531 / ``recv`` :594 — with the
backend lowered to the TPU fabric instead of NCCL/Gloo: group ops ride the
in-process rendezvous (host actors) and, inside jit, the ``ray_tpu.parallel``
axis collectives (psum/all_gather/ppermute over ICI).

The reference's rendezvous-through-a-named-actor (NCCLUniqueID store)
disappears: groups are fabric-local state, no unique-id exchange needed.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.parallel.collective import (
    _registry,
    allgather_tensor,
    allreduce_tensor,
    broadcast_tensor,
    destroy_collective_group,
    init_collective_group,
    reducescatter_tensor,
)


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: List[int],
    backend: str = "tpu",
    group_name: str = "default",
) -> None:
    """Declarative group creation (reference: collective.py:151): binds each
    actor to its rank so collective ops called from actor code resolve their
    rank automatically (no manual ``set_rank``), and pre-registers every
    rank's data-plane address so cross-process sends never wait on lazy
    registration."""
    if len(actors) != len(ranks) or len(ranks) != world_size:
        raise ValueError("actors/ranks/world_size mismatch")
    for group_rank in ranks:
        if not 0 <= group_rank < world_size:
            raise ValueError(f"rank {group_rank} out of range for world_size {world_size}")
    if len(set(ranks)) != world_size:
        raise ValueError("ranks must be unique")
    # Create the registry entry directly — init_collective_group would also
    # publish THIS (driver) process's address as ranks[0]'s endpoint, which
    # is wrong when that rank's actor lives elsewhere.
    _registry.destroy(group_name)
    _registry.get_or_create(group_name, world_size)

    # actor -> rank binding, readable from any process via the cluster KV
    binding = {}
    for actor, group_rank in zip(actors, ranks):
        actor_id = getattr(actor, "_actor_id", None)
        if actor_id is None:
            raise ValueError("create_collective_group expects actor handles")
        binding[actor_id.hex()] = group_rank
    _bind_group(group_name, world_size, binding)

    # Rank addresses are NOT pre-published here: each rank's process
    # registers its OWN endpoint at round start (_rendezvous_transport /
    # recv), which is the only address that's always right — a
    # process-worker actor's endpoint is the worker's own data server, not
    # its hosting node's, and the driver can't know which from here.


# group-name -> {actor_id_hex: rank}; mirrored in the KV for other processes
_group_bindings: Dict[str, Dict[str, int]] = {}
_bindings_lock = threading.Lock()


def _bind_group(group_name: str, world_size: int, binding: Dict[str, int]) -> None:
    import os
    import pickle

    with _bindings_lock:
        _group_bindings[group_name] = dict(binding)
    from ray_tpu.runtime.kv_client import get_kv

    kv = get_kv()
    if kv is not None:
        # epoch: unique per creation, so participant processes holding state
        # from an earlier same-named group reset instead of desyncing
        kv.put(
            f"rt_coll_grp/{group_name}".encode(),
            pickle.dumps(
                {
                    "world_size": world_size,
                    "binding": binding,
                    "epoch": os.urandom(8).hex(),
                },
                protocol=5,
            ),
        )


def _rank_from_actor_context(group_name: str) -> Optional[int]:
    """Declarative-binding fallback for _need_rank: the currently-executing
    actor's rank in the group, if bound via create_collective_group."""
    from ray_tpu.runtime.context import task_context

    current = task_context.current()
    if current is None:
        return None
    actor = current[0].actor_id()
    if actor.is_nil():
        return None
    aid = actor.hex()
    with _bindings_lock:
        binding = _group_bindings.get(group_name)
    if binding is None:
        import pickle

        from ray_tpu.runtime.kv_client import get_kv

        kv = get_kv()
        if kv is None:
            return None
        raw = kv.get(f"rt_coll_grp/{group_name}".encode())
        if raw is None:
            return None
        binding = pickle.loads(raw)["binding"]
        with _bindings_lock:
            _group_bindings[group_name] = binding
    return binding.get(aid)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _registry.get(group_name)
        return True
    except KeyError:
        return False


def get_collective_group_size(group_name: str = "default") -> int:
    return _registry.get(group_name).world_size


def _ensure_group(group_name: str) -> None:
    """Materialize a declaratively-created group in THIS process: an actor
    bound via create_collective_group never called init_collective_group
    here, so pull world_size from the group record in the KV.  The record's
    epoch detects a re-created group: stale local state (generation
    counters, cached bindings) resets instead of desyncing mailbox ids."""
    import pickle

    from ray_tpu.runtime import p2p
    from ray_tpu.runtime.kv_client import get_kv

    existing = None
    try:
        existing = _registry.get(group_name)
    except KeyError:
        pass
    kv = get_kv()
    if kv is None:
        return
    raw = kv.get(f"rt_coll_grp/{group_name}".encode())
    if raw is None:
        return
    record = pickle.loads(raw)
    epoch = record.get("epoch")
    if existing is not None and getattr(existing, "epoch", None) == epoch:
        return
    if existing is not None:
        _registry.destroy(group_name)
        p2p.forget_group(group_name)
        with _bindings_lock:
            _group_bindings.pop(group_name, None)
        # p2p FIFO counters belong to the dead incarnation
        with _p2p_lock:
            for key in [k for k in _p2p_send_seq if k[0] == group_name]:
                del _p2p_send_seq[key]
            for key in [k for k in _p2p_recv_seq if k[0] == group_name]:
                del _p2p_recv_seq[key]
    group = _registry.get_or_create(group_name, record["world_size"])
    group.epoch = epoch


def _group_epoch(group_name: str) -> str:
    try:
        return getattr(_registry.get(group_name), "epoch", "") or ""
    except KeyError:
        return ""


# ------------------------------------------------------------------- ops
def allreduce(tensor, group_name: str = "default", op: str = "sum", *, rank: Optional[int] = None):
    _ensure_group(group_name)
    return allreduce_tensor(tensor, _need_rank(rank, group_name), group_name, op)


def allgather(tensor, group_name: str = "default", *, rank: Optional[int] = None) -> List[Any]:
    _ensure_group(group_name)
    return allgather_tensor(tensor, _need_rank(rank, group_name), group_name)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default", *, rank: Optional[int] = None):
    _ensure_group(group_name)
    return broadcast_tensor(tensor, _need_rank(rank, group_name), src_rank, group_name)


def reducescatter(tensor, group_name: str = "default", *, rank: Optional[int] = None):
    _ensure_group(group_name)
    return reducescatter_tensor(tensor, _need_rank(rank, group_name), group_name)


def barrier(group_name: str = "default", *, rank: Optional[int] = None) -> None:
    _ensure_group(group_name)
    allreduce_tensor(0, _need_rank(rank, group_name), group_name)


# ---------------------------------------------------------- point-to-point
class _Mailboxes:
    def __init__(self):
        self.lock = threading.Lock()
        self.boxes: Dict[tuple, "_Box"] = {}

    def box(self, group: str, src: int, dst: int) -> "_Box":
        key = (group, src, dst)
        with self.lock:
            if key not in self.boxes:
                self.boxes[key] = _Box()
            return self.boxes[key]


class _Box:
    def __init__(self):
        self.cond = threading.Condition()
        self.items: list = []


_mail = _Mailboxes()


# per-process sequence counters for the cross-process (KV) channel: each
# (group, src, dst) pair is a FIFO stream; the sender numbers messages and
# the receiver consumes them in order
_p2p_send_seq: Dict[tuple, int] = {}
_p2p_recv_seq: Dict[tuple, int] = {}
_p2p_lock = threading.Lock()


def _reset_binding_state() -> None:
    """Runtime-shutdown reset (see parallel.collective.reset_module_state):
    bindings, mailboxes and FIFO counters all index a dead incarnation."""
    with _bindings_lock:
        _group_bindings.clear()
    with _p2p_lock:
        _p2p_send_seq.clear()
        _p2p_recv_seq.clear()
    with _mail.lock:
        _mail.boxes.clear()


def send(tensor, dst_rank: int, group_name: str = "default", *, rank: Optional[int] = None) -> None:
    """Reference: collective.py:531 — point-to-point send.

    Transport-native: across OS processes the message moves store-to-store
    on the chunked data plane (``runtime/p2p.py``) — a direct push into the
    destination process, never a value through the head KV.  Same-process
    ranks (no fabric endpoint) use in-memory mailboxes."""
    src = _need_rank(rank, group_name)
    from ray_tpu.parallel.collective import use_transport
    from ray_tpu.runtime import p2p

    _ensure_group(group_name)
    if use_transport(group_name):
        from ray_tpu.parallel.collective import _host_value

        with _p2p_lock:
            seq = _p2p_send_seq.get((group_name, src, dst_rank), 0)
            _p2p_send_seq[(group_name, src, dst_rank)] = seq + 1
        # make sure the counterpart can answer/see us before first contact
        p2p.register_rank(group_name, src)
        oid = p2p.mailbox_oid("p2p", group_name, _group_epoch(group_name), src, dst_rank, seq)
        # budget: the destination registers its address on ITS first op
        # (addresses are not pre-published — the binding process can't know
        # a worker-hosted rank's endpoint), so a sender may legitimately
        # wait for a receiver that is still loading; give it the collective
        # timeout, not resolve_rank's 30 s metadata default
        from ray_tpu.core.config import get_config

        p2p.post_to_rank(
            group_name, dst_rank, oid, _host_value(tensor),
            timeout=get_config().collective_timeout_s,
        )
        return
    box = _mail.box(group_name, src, dst_rank)
    with box.cond:
        box.items.append(tensor)
        box.cond.notify_all()


def recv(src_rank: int, group_name: str = "default", *, rank: Optional[int] = None, timeout: float = 120.0):
    """Reference: collective.py:594 — blocking point-to-point receive.

    Waits on the LOCAL store's condition variable (the inbound data-plane
    push wakes it) — no polling anywhere.  A mailbox wait that routed
    "inproc" WITHOUT proof (no multiprocess evidence yet) re-checks the
    routing every 250 ms and switches to the transport mid-wait — the same
    self-heal the rendezvous path gets from its _ReRoute escape."""
    import time as _time

    dst = _need_rank(rank, group_name)
    from ray_tpu.parallel.collective import use_transport
    from ray_tpu.runtime import p2p

    _ensure_group(group_name)
    deadline = _time.monotonic() + timeout
    if use_transport(group_name):
        return _recv_transport(src_rank, dst, group_name, timeout)
    box = _mail.box(group_name, src_rank, dst)
    with box.cond:
        while not box.items:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"recv from rank {src_rank} timed out")
            box.cond.wait(min(0.25, remaining))
            if not box.items and use_transport(group_name):
                break
        else:
            return box.items.pop(0)
    # routing evidence appeared mid-wait: finish the receive on the transport
    return _recv_transport(
        src_rank, dst, group_name, max(0.0, deadline - _time.monotonic())
    )


def _recv_transport(src_rank: int, dst: int, group_name: str, timeout: float):
    from ray_tpu.exceptions import GetTimeoutError
    from ray_tpu.runtime import p2p

    # publish where this rank lives so senders can reach us
    p2p.register_rank(group_name, dst)
    with _p2p_lock:
        seq = _p2p_recv_seq.get((group_name, src_rank, dst), 0)
    oid = p2p.mailbox_oid("p2p", group_name, _group_epoch(group_name), src_rank, dst, seq)
    try:
        value = p2p.take_group(group_name, oid, timeout)
    except GetTimeoutError as exc:
        # only a genuine wait expiry maps to TimeoutError — endpoint /
        # store failures propagate with their real cause
        raise TimeoutError(f"recv from rank {src_rank} timed out") from exc
    # consume the sequence number only on success — a timed-out recv
    # must retry the SAME slot, or the FIFO desyncs
    with _p2p_lock:
        _p2p_recv_seq[(group_name, src_rank, dst)] = seq + 1
    return value


# ----------------------------------------------------------------- helpers
_rank_local = threading.local()


def set_rank(rank: int) -> None:
    """Bind this thread's rank (actors call once; the reference infers rank
    from the actor registered in the group)."""
    _rank_local.value = rank


def _need_rank(rank: Optional[int], group_name: str = "default") -> int:
    if rank is not None:
        return rank
    r = getattr(_rank_local, "value", None)
    if r is not None:
        return r
    # declarative binding: the executing actor's rank from
    # create_collective_group (reference: collective.py:151 infers rank
    # from the registered actor)
    r = _rank_from_actor_context(group_name)
    if r is None:
        raise ValueError(
            "rank not set: pass rank=, call collective.set_rank(rank), or bind "
            "this actor via create_collective_group"
        )
    return r


__all__ = [
    "allgather",
    "allreduce",
    "barrier",
    "broadcast",
    "create_collective_group",
    "destroy_collective_group",
    "get_collective_group_size",
    "init_collective_group",
    "is_group_initialized",
    "recv",
    "reducescatter",
    "send",
    "set_rank",
]
