"""ActorPool (parity: python/ray/util/actor_pool.py).

Schedules a stream of tasks over a fixed set of actors, returning results
in submission order (``map``) or completion order (``map_unordered``).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Tuple

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[Tuple[Callable, Any]] = []

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queues if all actors busy."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: float = None) -> Any:
        if not self.has_next():
            raise StopIteration("no more results")
        future = self._index_to_future[self._next_return_index]
        if timeout is not None:
            ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
            if not ready:
                # leave all state intact so the caller can retry
                raise TimeoutError("timed out waiting for result")
        # settle bookkeeping BEFORE get: a raising task must still return its
        # actor to the pool and advance the return cursor (the reference pops
        # the future first for the same reason)
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._return_actor(self._future_to_actor.pop(future))
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: float = None) -> Any:
        if not self.has_next():
            raise StopIteration("no more results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        for idx, fut in list(self._index_to_future.items()):
            if fut is future:
                del self._index_to_future[idx]
                break
        self._return_actor(self._future_to_actor.pop(future))
        return ray_tpu.get(future)

    def map(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor) -> None:
        self._return_actor(actor)
