"""Serializability inspection (parity: python/ray/util/check_serialize.py)."""

from __future__ import annotations

from typing import Any, Set, Tuple


def inspect_serializability(obj: Any, name: str = None) -> Tuple[bool, Set[str]]:
    """Returns (is_serializable, set_of_problem_descriptions)."""
    problems: Set[str] = set()
    _check(obj, name or repr(obj), problems, depth=0)
    return (not problems, problems)


def _check(obj: Any, name: str, problems: Set[str], depth: int) -> None:
    import cloudpickle

    if depth > 3:
        return
    try:
        cloudpickle.dumps(obj)
        return
    except Exception as exc:  # noqa: BLE001
        problems.add(f"{name}: {type(exc).__name__}: {exc}")
    # Drill into closures/attributes to find the offending member.
    closure = getattr(obj, "__closure__", None)
    if closure:
        names = obj.__code__.co_freevars
        for var, cell in zip(names, closure):
            try:
                cloudpickle.dumps(cell.cell_contents)
            except Exception:
                _check(cell.cell_contents, f"{name}.<closure>.{var}", problems, depth + 1)
    d = getattr(obj, "__dict__", None)
    if isinstance(d, dict):
        for k, v in list(d.items())[:20]:
            try:
                cloudpickle.dumps(v)
            except Exception:
                _check(v, f"{name}.{k}", problems, depth + 1)
