"""ParallelIterator: sharded lazy iteration over actors.

Parity: ``python/ray/util/iter.py`` (1.3k LoC) — the pre-Ray-Data parallel
iterator API.  Each shard is an actor pulling from its own item source;
transforms (``for_each``/``filter``/``batch``/``flat_map``) compose lazily
per shard; ``gather_sync``/``gather_async`` merge shards on the driver.
Kept compact here because ``ray_tpu.data`` is the modern path (the
reference deprecated this module in favor of Datasets too) — but the API
works, it is not a stub.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class _ShardActor:
    """Owns one shard's item source and applies its transform chain."""

    def __init__(self, make_source):
        self._make_source = make_source
        self._it: Iterator = iter(make_source())

    def next_batch(self, ops: List[tuple], n: int = 64) -> tuple:
        """Up to n transformed items + done flag (one RPC per wave, not per
        item — the per-item actor-call tax is what killed the original)."""
        out: List[Any] = []
        done = False
        while len(out) < n:
            try:
                item = next(self._it)
            except StopIteration:
                done = True
                break
            items = [item]
            for kind, fn in ops:
                if kind == "for_each":
                    items = [fn(x) for x in items]
                elif kind == "filter":
                    items = [x for x in items if fn(x)]
                elif kind == "flat_map":
                    items = [y for x in items for y in fn(x)]
            out.extend(items)
        return out, done

    def reset(self) -> None:
        self._it = iter(self._make_source())


class LocalIterator:
    """Driver-side iterator over gathered shard output
    (parity: util.iter.LocalIterator)."""

    def __init__(self, gen_factory: Callable[[], Iterator]):
        self._factory = gen_factory

    def __iter__(self):
        return self._factory()

    def for_each(self, fn: Callable) -> "LocalIterator":
        factory = self._factory
        return LocalIterator(lambda: (fn(x) for x in factory()))

    def filter(self, fn: Callable) -> "LocalIterator":
        factory = self._factory
        return LocalIterator(lambda: (x for x in factory() if fn(x)))

    def batch(self, n: int) -> "LocalIterator":
        factory = self._factory

        def gen():
            it = factory()
            while True:
                block = list(itertools.islice(it, n))
                if not block:
                    return
                yield block

        return LocalIterator(gen)

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(iter(self), n))


class ParallelIterator:
    """A sharded iterator over actors (parity: util.iter.ParallelIterator)."""

    _batch_n = None  # set by batch(): gather re-chunks to this size

    def __init__(self, sources: List[Callable[[], Iterable]], ops: List[tuple] = ()):  # noqa: B006
        self._sources = sources
        self._ops = list(ops)
        self._actors: List[Any] = []

    # ----------------------------------------------------------- lazy ops
    def for_each(self, fn: Callable) -> "ParallelIterator":
        return ParallelIterator(self._sources, self._ops + [("for_each", fn)])

    def filter(self, fn: Callable) -> "ParallelIterator":
        return ParallelIterator(self._sources, self._ops + [("filter", fn)])

    def flat_map(self, fn: Callable) -> "ParallelIterator":
        return ParallelIterator(self._sources, self._ops + [("flat_map", fn)])

    def batch(self, n: int) -> "ParallelIterator":
        # batching happens driver-side on gather (shard waves re-chunk)
        out = ParallelIterator(self._sources, self._ops)
        out._batch_n = n
        return out

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        if self._ops or other._ops:
            raise ValueError("union() must be applied before transforms")
        return ParallelIterator(self._sources + other._sources)

    def num_shards(self) -> int:
        return len(self._sources)

    # ------------------------------------------------------------- gather
    def _ensure_actors(self) -> List[Any]:
        if not self._actors:
            self._actors = [_ShardActor.remote(src) for src in self._sources]
        return self._actors

    def gather_sync(self) -> LocalIterator:
        """Round-robin over shards, in order (parity: gather_sync)."""
        outer = self

        def gen():
            actors = outer._ensure_actors()
            for a in actors:
                ray_tpu.get(a.reset.remote())
            live = {i: a for i, a in enumerate(actors)}
            batch_n = getattr(outer, "_batch_n", None)
            while live:
                for i, a in list(live.items()):
                    items, done = ray_tpu.get(a.next_batch.remote(outer._ops))
                    if batch_n:
                        for j in range(0, len(items), batch_n):
                            yield items[j : j + batch_n]
                    else:
                        yield from items
                    if done:
                        del live[i]

        return LocalIterator(gen)

    def gather_async(self) -> LocalIterator:
        """Merge shards by completion order (parity: gather_async)."""
        outer = self

        def gen():
            actors = outer._ensure_actors()
            for a in actors:
                ray_tpu.get(a.reset.remote())
            pending = {a.next_batch.remote(outer._ops): a for a in actors}
            batch_n = getattr(outer, "_batch_n", None)
            while pending:
                ready, _ = ray_tpu.wait(list(pending), num_returns=1)
                ref = ready[0]
                actor = pending.pop(ref)
                items, done = ray_tpu.get(ref)
                if not done:
                    pending[actor.next_batch.remote(outer._ops)] = actor
                if batch_n:
                    for j in range(0, len(items), batch_n):
                        yield items[j : j + batch_n]
                else:
                    yield from items

        return LocalIterator(gen)

    def take(self, n: int) -> List[Any]:
        return self.gather_sync().take(n)

    def show(self, n: int = 20) -> None:
        for item in self.take(n):
            print(item)

    def __repr__(self) -> str:
        return f"ParallelIterator(shards={len(self._sources)}, ops={len(self._ops)})"


# ----------------------------------------------------------- constructors
def from_items(items: List[Any], num_shards: int = 2) -> ParallelIterator:
    shards = [list(items[i::num_shards]) for i in range(num_shards)]
    return ParallelIterator([(lambda s=s: s) for s in shards if s or True])


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    def make(i):
        return lambda: range(i, n, num_shards)

    return ParallelIterator([make(i) for i in range(num_shards)])


def from_iterators(generators: List[Callable[[], Iterable]]) -> ParallelIterator:
    return ParallelIterator(list(generators))
