"""multiprocessing.Pool API over remote tasks.

Parity: ``python/ray/util/multiprocessing/`` — a drop-in ``Pool`` whose
``apply/map/starmap/imap`` fan work out as tasks instead of forked
processes, so the same code scales past one host.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        import ray_tpu as rt

        out = rt.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        import ray_tpu as rt

        rt.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        import ray_tpu as rt

        ready, _ = rt.wait(self._refs, num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Task-backed process pool (``ray.util.multiprocessing.Pool`` parity).

    ``processes`` bounds in-flight chunks (the runtime's scheduler does the
    real placement); ``chunksize`` groups items per task like stdlib's Pool.
    """

    def __init__(self, processes: Optional[int] = None, initializer=None, initargs=()):
        import ray_tpu as rt

        if not rt.is_initialized():
            rt.init()
        self._rt = rt
        self._processes = processes or 8
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    # ------------------------------------------------------------------
    def _chunk_runner(self, func):
        init, initargs = self._initializer, self._initargs

        def run_chunk(chunk):
            if init is not None:
                init(*initargs)
            return [func(*args) for args in chunk]

        return run_chunk

    def apply(self, func: Callable, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args=(), kwds=None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}
        remote_fn = self._rt.remote(lambda: func(*args, **kwds))
        return AsyncResult([remote_fn.remote()], single=True)

    def map(self, func: Callable, iterable: Iterable, chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap(func, ((x,) for x in iterable), chunksize)

    def map_async(self, func, iterable, chunksize: Optional[int] = None) -> AsyncResult:
        return self.starmap_async(func, ((x,) for x in iterable), chunksize)

    def starmap(self, func: Callable, iterable: Iterable, chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable, chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        runner = self._rt.remote(self._chunk_runner(func))
        refs = [
            runner.remote(items[i : i + chunksize]) for i in range(0, len(items), chunksize)
        ]
        return _ChunkedAsyncResult(refs)

    def imap(self, func: Callable, iterable: Iterable, chunksize: int = 1):
        self._check_open()
        runner = self._rt.remote(self._chunk_runner(func))
        items = list(iterable)
        refs = [
            runner.remote([(x,) for x in items[i : i + chunksize]])
            for i in range(0, len(items), chunksize)
        ]
        for ref in refs:  # ordered
            yield from self._rt.get(ref)

    def imap_unordered(self, func: Callable, iterable: Iterable, chunksize: int = 1):
        self._check_open()
        runner = self._rt.remote(self._chunk_runner(func))
        items = list(iterable)
        refs = [
            runner.remote([(x,) for x in items[i : i + chunksize]])
            for i in range(0, len(items), chunksize)
        ]
        pending = list(refs)
        while pending:
            ready, pending = self._rt.wait(pending, num_returns=1)
            yield from self._rt.get(ready[0])

    # ------------------------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        pass

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool is closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _ChunkedAsyncResult(AsyncResult):
    def __init__(self, refs):
        super().__init__(refs, single=False)

    def get(self, timeout: Optional[float] = None):
        import ray_tpu as rt

        chunks = rt.get(self._refs, timeout=timeout)
        return list(itertools.chain.from_iterable(chunks))
