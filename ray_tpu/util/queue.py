"""Distributed Queue backed by a named actor
(parity: python/ray/util/queue.py)."""

from __future__ import annotations

import queue as _stdlib_queue
import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self.q = _stdlib_queue.Queue(maxsize=maxsize)

    def put_nowait(self, item) -> bool:
        try:
            self.q.put_nowait(item)
            return True
        except _stdlib_queue.Full:
            return False

    def get_nowait(self):
        try:
            return (True, self.q.get_nowait())
        except _stdlib_queue.Empty:
            return (False, None)

    def qsize(self) -> int:
        return self.q.qsize()

    def empty(self) -> bool:
        return self.q.empty()

    def full(self) -> bool:
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("execution", "inproc")
        opts.setdefault("max_concurrency", 8)
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full()
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok = ray_tpu.get(self.actor.put_nowait.remote(item))
            if ok:
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise Full()
            time.sleep(0.005)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty()
            return item
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if ok:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty()
            time.sleep(0.005)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
