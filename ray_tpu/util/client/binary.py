"""Binary client protocol: the cross-language (C++) frontend wire format.

Parity: the reference's C++ user API (``cpp/include/ray/api/``) and
cross-language calls (``python/ray/cross_language.py``) — a native client
puts/gets byte objects and invokes Python functions by importable name.
The counterpart C++ library lives in ``ray_tpu/native/src/client.cpp``
(``ray_tpu/native/include/ray_tpu_client.h``).

Wire format (little-endian), after the 8-byte magic ``RTCPBIN1``:

    request:  u32 payload_len | u8 op | u64 rid | payload
    reply:    u32 payload_len | u8 status (0 ok, 1 error) | u64 rid | payload

Ops:
    1 PING                                  -> b"pong"
    2 PUT      raw bytes                    -> 16-byte ref id
    3 GET      16B ref id                   -> value bytes (see encoding)
    4 CALL     u16 name_len | name utf8 ("module:function")
               u8 nargs | per-arg: u8 kind | u32 len | data
                                            -> 16-byte ref id
    5 RELEASE  16B ref id                   -> empty

Arg kinds: 0 raw bytes, 1 ref id (resolves to the object), 2 utf-8 str,
3 f64, 4 i64. GET value encoding: bytes pass through; str utf-8; int/float
rendered as their decimal utf-8 text (native callers parse); other types
are an error — cross-language results should be bytes.
"""

from __future__ import annotations

import importlib
import struct
import threading
import uuid
from typing import Any, Dict

from ray_tpu.util.client.common import _recv_exact as recv_exact

BINARY_MAGIC = b"RTCPBIN1"

_REQ_HEAD = struct.Struct("<IBQ")   # payload_len, op, rid
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_F64 = struct.Struct("<d")
_I64 = struct.Struct("<q")

OP_PING = 1
OP_PUT = 2
OP_GET = 3
OP_CALL = 4
OP_RELEASE = 5

_fn_cache: Dict[str, Any] = {}
_fn_lock = threading.Lock()


def _resolve_function(name: str):
    with _fn_lock:
        fn = _fn_cache.get(name)
    if fn is not None:
        return fn
    if ":" not in name:
        raise ValueError(f"cross-language function name must be 'module:attr', got {name!r}")
    module_name, attr = name.split(":", 1)
    target = importlib.import_module(module_name)
    for part in attr.split("."):
        target = getattr(target, part)
    if not callable(target):
        raise TypeError(f"{name!r} is not callable")
    with _fn_lock:
        _fn_cache[name] = target
    return target


def _decode_args(session, payload: bytes, offset: int):
    (nargs,) = struct.unpack_from("<B", payload, offset)
    offset += 1
    args = []
    for _ in range(nargs):
        (kind,) = struct.unpack_from("<B", payload, offset)
        (length,) = _U32.unpack_from(payload, offset + 1)
        data = payload[offset + 5 : offset + 5 + length]
        offset += 5 + length
        if kind == 0:
            args.append(bytes(data))
        elif kind == 1:
            with session.lock:
                args.append(session.refs[bytes(data)])
        elif kind == 2:
            args.append(data.decode("utf-8"))
        elif kind == 3:
            args.append(_F64.unpack(data)[0])
        elif kind == 4:
            args.append(_I64.unpack(data)[0])
        else:
            raise ValueError(f"unknown arg kind {kind}")
    if offset != len(payload):
        # a truncated/overlong request must fail loudly, not silently run
        # with the wrong argument list
        raise ValueError(
            f"malformed CALL payload: {len(payload) - offset} trailing bytes"
        )
    return args


def _encode_value(value: Any) -> bytes:
    if isinstance(value, (bytes, bytearray, memoryview)):
        return bytes(value)
    if isinstance(value, str):
        return value.encode("utf-8")
    if isinstance(value, bool):
        return b"1" if value else b"0"   # before int: bool IS int in Python
    if isinstance(value, (int, float)):
        return repr(value).encode("utf-8")
    raise TypeError(
        f"cross-language GET needs bytes/str/int/float, got {type(value).__name__}"
    )


def serve_binary(rt, session, conn, stop_event=None) -> None:
    """Request loop for one binary-mode connection (requests handled
    serially — native clients multiplex by opening more connections)."""
    while stop_event is None or not stop_event.is_set():
        head = recv_exact(conn, _REQ_HEAD.size)
        payload_len, op, rid = _REQ_HEAD.unpack(head)
        payload = recv_exact(conn, payload_len) if payload_len else b""
        try:
            out = _dispatch(rt, session, op, payload)
            status = 0
        except BaseException as exc:  # noqa: BLE001 — errors cross the wire
            out = repr(exc).encode("utf-8")
            status = 1
        conn.sendall(_REQ_HEAD.pack(len(out), status, rid) + out)


def _dispatch(rt, session, op: int, payload: bytes) -> bytes:
    if op == OP_PING:
        return b"pong"
    if op == OP_PUT:
        ref = rt.put(bytes(payload))
        ref_id = uuid.uuid4().bytes
        with session.lock:
            session.refs[ref_id] = ref
        return ref_id
    if op == OP_GET:
        ref_id = bytes(payload[:16])
        timeout = _F64.unpack_from(payload, 16)[0] if len(payload) >= 24 else None
        if timeout is not None and timeout < 0:
            timeout = None
        with session.lock:
            ref = session.refs[ref_id]
        return _encode_value(rt.get(ref, timeout=timeout))
    if op == OP_CALL:
        (name_len,) = _U16.unpack_from(payload, 0)
        name = payload[2 : 2 + name_len].decode("utf-8")
        args = _decode_args(session, payload, 2 + name_len)
        fn = _resolve_function(name)
        ref = rt.remote(fn).remote(*args)
        ref_id = uuid.uuid4().bytes
        with session.lock:
            session.refs[ref_id] = ref
        return ref_id
    if op == OP_RELEASE:
        with session.lock:
            session.refs.pop(bytes(payload[:16]), None)
        return b""
    raise ValueError(f"unknown binary op {op}")
