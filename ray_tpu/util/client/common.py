"""Wire protocol for thin-client mode.

Parity with the reference Ray Client protocol
(``src/ray/protobuf/ray_client.proto``, design in
``python/ray/util/client/ARCHITECTURE.md:1``): the reference rides gRPC
streams; here the same request/response shapes ride length-prefixed
cloudpickle frames over TCP, with request-id multiplexing so many client
threads can have calls in flight on one connection.
"""

from __future__ import annotations

import socket
import struct
from typing import Any

import cloudpickle

_HEADER = struct.Struct(">Q")
MAX_FRAME = 1 << 34  # 16 GiB sanity bound


def send_msg(sock: socket.socket, msg: Any) -> None:
    payload = cloudpickle.dumps(msg)
    sock.sendall(_HEADER.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket, preread_header: bytes | None = None) -> Any:
    header = preread_header if preread_header is not None else _recv_exact(sock, _HEADER.size)
    (length,) = _HEADER.unpack(header)
    if length > MAX_FRAME:
        raise ConnectionError(f"frame too large: {length}")
    return cloudpickle.loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("connection closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class RefMarker:
    """Placeholder for a client-held ObjectRef inside pickled args; the
    server swaps it for the real ref (reference: ClientObjectRef ids in
    ray_client.proto Args)."""

    __slots__ = ("id",)

    def __init__(self, ref_id: bytes):
        self.id = ref_id


class ActorMarker:
    """Placeholder for a client-held actor handle inside pickled args."""

    __slots__ = ("id",)

    def __init__(self, actor_id: bytes):
        self.id = actor_id


def translate(obj: Any, ref_fn, actor_fn) -> Any:
    """Shallow-walk containers swapping client refs/handles via the given
    translators (the reference also only walks top-level containers)."""
    if isinstance(obj, RefMarker):
        return ref_fn(obj)
    if isinstance(obj, ActorMarker):
        return actor_fn(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(translate(x, ref_fn, actor_fn) for x in obj)
    if isinstance(obj, dict):
        return {k: translate(v, ref_fn, actor_fn) for k, v in obj.items()}
    return obj
