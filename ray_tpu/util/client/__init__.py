"""Thin-client mode (``ray://``): a lightweight client driving a remote
runtime over a socket, parity with ``python/ray/util/client/``."""

from ray_tpu.util.client.server import ClientServer
from ray_tpu.util.client.worker import (
    ClientActorHandle,
    ClientContext,
    ClientObjectRef,
    connect,
)

__all__ = [
    "ClientServer",
    "ClientContext",
    "ClientObjectRef",
    "ClientActorHandle",
    "connect",
]
